"""Dgraph failure modes (reference:
/root/reference/dgraph/src/jepsen/dgraph/nemesis.clj:1-180): alpha
killer/fixer, zero killer, the tablet mover, clock skews, and
partitions, composed behind one routed nemesis with a generator built
from option flags.

In the hermetic suite both alpha and zero map onto the single dgraph
sim daemon; the tablet mover drives the sim's /state + /moveTablet
surface, which reshuffles predicate → group assignments the same way
zero's API does."""

from __future__ import annotations

import json
import logging
import random
import urllib.parse
import urllib.request

from .. import generator as gen, nemesis, trace, util
from ..control import util as cu
from ..history import Op
from ..nemesis import Nemesis
from ..nemesis import time as nt
from ..util import random_nonempty_subset

log = logging.getLogger("jepsen_tpu.dbs.dgraph")


def _stop_daemon(db):
    def stop(test, node):
        cu.stop_daemon(test["remote"], node,
                       f"{db.suite.dir(test, node)}/{db.pid_name}")
        return "killed"

    return stop


def _start_daemon(db):
    def start(test, node):
        db.start(test, node)
        return "started"

    return start


def alpha_killer(db) -> Nemesis:
    """:start kills alpha on EVERY node, :stop revives
    (nemesis.clj:15-21 — the identity targeter is deliberate)."""
    return nemesis.node_start_stopper(
        lambda nodes: nodes, _stop_daemon(db), _start_daemon(db))


def zero_killer(db) -> Nemesis:
    """:start kills zero on a random nonempty subset
    (nemesis.clj:41-47)."""
    return nemesis.node_start_stopper(
        random_nonempty_subset, _stop_daemon(db), _start_daemon(db))


class AlphaFixer(Nemesis):
    """Speculative restarts: alpha likes to fall over if zero isn't
    around on startup (nemesis.clj:23-39)."""

    def __init__(self, db):
        self.db = db

    def invoke(self, test, op: Op) -> Op:
        remote = test["remote"]
        targets = random_nonempty_subset(list(test["nodes"]))

        def fix(node):
            pidfile = (f"{self.db.suite.dir(test, node)}/"
                       f"{self.db.pid_name}")
            if cu.daemon_running(remote, node, pidfile):
                return "already-running"
            self.db.start(test, node)
            return "restarted"

        return op.with_(type="info",
                        value=dict(zip(targets,
                                       util.real_pmap(fix, targets))))


class TabletMover(Nemesis):
    """Moves tablets (predicates) between groups at random via zero's
    state/moveTablet API (nemesis.clj:49-86)."""

    def __init__(self, suite):
        self.suite = suite

    def _get_state(self, test, node) -> dict:
        url = (f"http://{self.suite.host(test, node)}:"
               f"{self.suite.port(test, node)}/state")
        with urllib.request.urlopen(url, timeout=5) as resp:
            return json.load(resp)

    def _move(self, test, node, pred: str, group: str) -> None:
        q = urllib.parse.urlencode({"tablet": pred, "group": group})
        url = (f"http://{self.suite.host(test, node)}:"
               f"{self.suite.port(test, node)}/moveTablet?{q}")
        req = urllib.request.Request(url, method="POST", data=b"{}")
        with urllib.request.urlopen(req, timeout=5) as resp:
            resp.read()

    def invoke(self, test, op: Op) -> Op:
        with trace.with_trace("nemesis.tablet-mover.invoke"):
            node = random.choice(list(test["nodes"]))
            try:
                state = self._get_state(test, node)
            except OSError:
                return op.with_(type="info", value="timeout")
            groups_map = state.get("groups") or {}
            groups = list(groups_map)
            tablets = [t for g in groups_map.values()
                       for t in (g.get("tablets") or {}).values()]
            random.shuffle(tablets)
            moved = {}
            for tablet in tablets:
                pred = tablet["predicate"]
                group = str(tablet["groupId"])
                group2 = random.choice(groups) if groups else group
                if group != group2:
                    log.info("Moving %s from %s to %s",
                             pred, group, group2)
                    try:
                        self._move(test, node, pred, group2)
                    except OSError:
                        moved[pred] = "timeout"
                        continue
                    moved[pred] = [group, group2]
            return op.with_(type="info", value=moved)


class BumpTimeSkew(Nemesis):
    """On :start, bump the clock by dt ms on a random half of the
    nodes; on :stop, reset all clocks (nemesis.clj:88-112)."""

    def __init__(self, dt_ms: int):
        self.dt_ms = dt_ms

    def setup(self, test):
        # Shared clock bring-up (install native bump-time tool, stop
        # ntpd, best-effort reset) — without the install the first
        # :start would crash on a missing /opt binary.
        nt.bring_up(test)
        return self

    def invoke(self, test, op: Op) -> Op:
        remote = test["remote"]
        if op.f == "start":
            def bump(node):
                if random.random() < 0.5:
                    nt.bump_time(remote, node, self.dt_ms)
                    return self.dt_ms
                return 0

            nodes = list(test["nodes"])
            return op.with_(type="info",
                            value=dict(zip(nodes,
                                           util.real_pmap(bump, nodes))))
        if op.f == "stop":
            for node in test["nodes"]:
                nt.try_reset(remote, node)
            return op.with_(type="info", value="reset")
        raise ValueError(f"bump-time can't handle {op.f!r}")

    def teardown(self, test):
        for node in test["nodes"]:
            nt.try_reset(test["remote"], node)


SKEWS = {"huge": 7500, "big": 2000, "small": 250, "tiny": 100}


def skew(opts: dict) -> BumpTimeSkew:
    """Named skew magnitudes (nemesis.clj:114-120)."""
    return BumpTimeSkew(SKEWS.get(opts.get("skew"), 0))


class _FMap(dict):
    """A dict usable as a compose routing key (hashable by identity)."""

    __hash__ = object.__hash__


def full_nemesis(db, opts: dict) -> Nemesis:
    """The enabled failure modes behind one routed nemesis
    (nemesis.clj:122-138 composes every mode; here only flagged modes
    join the composition so their setup hooks — e.g. the partitioners'
    net heal — only run when that fault surface is in play)."""
    routes: dict = {}
    if opts.get("fix_alpha"):
        routes[frozenset({"fix-alpha"})] = AlphaFixer(db)
    if opts.get("kill_alpha"):
        routes[_FMap({"kill-alpha": "start",
                      "restart-alpha": "stop"})] = alpha_killer(db)
    if opts.get("kill_zero"):
        routes[_FMap({"kill-zero": "start",
                      "restart-zero": "stop"})] = zero_killer(db)
    if opts.get("move_tablet"):
        routes[frozenset({"move-tablet"})] = TabletMover(db.suite)
    if opts.get("partition_halves"):
        routes[_FMap({"start-partition-halves": "start",
                      "stop-partition-halves": "stop"})] = \
            nemesis.partition_random_halves()
    if opts.get("partition_ring"):
        routes[_FMap({"start-partition-ring": "start",
                      "stop-partition-ring": "stop"})] = \
            nemesis.partition_majorities_ring()
    if opts.get("skew_clock"):
        routes[_FMap({"start-skew": "start",
                      "stop-skew": "stop"})] = skew(opts)
    return nemesis.compose(routes)


def _op(f: str) -> dict:
    return {"type": "info", "f": f}


FLAG_CYCLES = [
    ("kill_alpha", ["kill-alpha", "restart-alpha"]),
    ("kill_zero", ["kill-zero", "restart-zero"]),
    ("fix_alpha", ["fix-alpha"]),
    ("partition_halves", ["start-partition-halves",
                          "stop-partition-halves"]),
    ("partition_ring", ["start-partition-ring", "stop-partition-ring"]),
    ("skew_clock", ["start-skew", "stop-skew"]),
    ("move_tablet", ["move-tablet"]),
]


def full_generator(opts: dict) -> gen.Generator | None:
    """A mix of op cycles for each enabled failure mode, staggered by
    `interval` (nemesis.clj:140-167)."""
    import itertools

    gens = [gen.seq(itertools.cycle([_op(f) for f in fs]))
            for flag, fs in FLAG_CYCLES if opts.get(flag)]
    if not gens:
        return None
    mixed = gen.mix(gens)
    interval = opts.get("interval", 10)
    return gen.stagger(interval, mixed) if interval > 0 else mixed


FINAL_FS = [("partition_halves", "stop-partition-halves"),
            ("partition_ring", "stop-partition-ring"),
            ("skew_clock", "stop-skew"),
            ("kill_zero", "restart-zero"),
            ("kill_alpha", "restart-alpha")]


def final_generator(opts: dict) -> gen.Generator | None:
    """Heal everything at the end, slightly delayed
    (nemesis.clj:169-180)."""
    fs = [f for flag, f in FINAL_FS if opts.get(flag)]
    if not fs:
        return None
    final = gen.seq([_op(f) for f in fs])
    delay = opts.get("final_delay", 5)
    return gen.delay(delay, final) if delay > 0 else final


def package(db, opts: dict) -> dict | None:
    """{'nemesis', 'generator', 'final_generator'} when any failure
    flag is set, else None (the suite keeps its default)."""
    generator = full_generator(opts)
    if generator is None:
        return None
    return {"nemesis": full_nemesis(db, opts),
            "generator": generator,
            "final_generator": final_generator(opts)}
