"""Hermetic tidb cluster archive: the pd/tikv/tidb TRIPLE.

The real deployment runs three daemons per node with ordered bring-up
(/root/reference/tidb/src/tidb/db.clj:14-223: pd quorum, then tikv,
then tidb). The archive mirrors that shape: `pd-server` and
`tikv-server` are role placeholders (dbs/role_sim — real pids, ports,
logs; kill/restart targets), `tidb-server` is the MySQL-protocol sim
(dbs/mysql_sim) that actually serves SQL. All three share the same
state file, standing in for tikv's replicated store.
"""

from __future__ import annotations

from .simbase import build_multi_sim_archive


def build_archive(dest: str, data_path: str, mean_latency: float = 0.0,
                  python: str | None = None) -> str:
    return build_multi_sim_archive(
        dest, "tidb-sim",
        {
            "pd-server": "jepsen_tpu.dbs.role_sim",
            "tikv-server": "jepsen_tpu.dbs.role_sim",
            "tidb-server": "jepsen_tpu.dbs.mysql_sim",
        },
        data_path, mean_latency=mean_latency, python=python,
    )
