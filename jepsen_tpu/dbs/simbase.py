"""Shared machinery for the hermetic protocol simulators (etcd_sim,
zk_sim): the flock-guarded JSON state store that makes a multi-process
simulated cluster linearizable by construction, and the tarball builder
that packages a simulator as an installable "database binary" for the
suites' normal install_archive path."""

from __future__ import annotations

import fcntl
import json
import os
import shlex
import sys
import tempfile


class Store:
    """Shared, flock-serialized JSON state."""

    def __init__(self, path: str):
        self.path = path
        self.lock_path = path + ".lock"
        # Touch the lock file so flock always has a target.
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        open(self.lock_path, "a").close()

    def _load(self) -> dict:
        try:
            with open(self.path) as f:
                return json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            return {}

    def _save(self, data: dict) -> None:
        fd, tmp = tempfile.mkstemp(
            dir=os.path.dirname(os.path.abspath(self.path)) or "."
        )
        with os.fdopen(fd, "w") as f:
            json.dump(data, f)
        os.replace(tmp, self.path)

    def transact(self, fn):
        """Run fn(state-dict) -> (result, new-state|None) under the
        exclusive lock; None keeps the state unchanged."""
        with open(self.lock_path, "a") as lockf:
            fcntl.flock(lockf, fcntl.LOCK_EX)
            try:
                data = self._load()
                result, new = fn(data)
                if new is not None:
                    self._save(new)
                return result
            finally:
                fcntl.flock(lockf, fcntl.LOCK_UN)


class StoreTxn:
    """An explicit multi-round-trip transaction on a Store: holds the
    flock from begin() to commit()/rollback(), with a bounded
    acquisition wait so contending transactions fail fast instead of
    queueing forever (crdb_sim surfaces that as SQLSTATE 40001, the
    shape of CockroachDB's 'restart transaction' errors)."""

    def __init__(self, store: Store):
        self.store = store
        self._lockf = None
        self.data: dict | None = None

    @property
    def active(self) -> bool:
        return self._lockf is not None

    def begin(self, timeout: float = 2.0) -> bool:
        """True if the lock was acquired and a working snapshot loaded;
        False on acquisition timeout."""
        import time as _time

        assert not self.active, "transaction already open"
        lockf = open(self.store.lock_path, "a")
        deadline = _time.monotonic() + timeout
        while True:
            try:
                fcntl.flock(lockf, fcntl.LOCK_EX | fcntl.LOCK_NB)
                break
            except OSError:
                if _time.monotonic() >= deadline:
                    lockf.close()
                    return False
                _time.sleep(0.005)
        self._lockf = lockf
        self.data = self.store._load()
        return True

    def commit(self) -> None:
        assert self.active, "no transaction open"
        try:
            self.store._save(self.data)
        finally:
            self._release()

    def rollback(self) -> None:
        if self.active:
            self._release()

    def _release(self) -> None:
        fcntl.flock(self._lockf, fcntl.LOCK_UN)
        self._lockf.close()
        self._lockf = None
        self.data = None


def build_sim_archive(dest: str, module: str, binary: str, arcname: str,
                      data_path: str, mean_latency: float = 0.0,
                      python: str | None = None) -> str:
    """Build a tar.gz whose `binary` is a script launching `module`
    (a jepsen_tpu.dbs simulator) with a shared state file."""
    return build_multi_sim_archive(
        dest, arcname, {binary: module}, data_path,
        mean_latency=mean_latency, python=python)


def build_multi_sim_archive(dest: str, arcname: str, binaries: dict,
                            data_path: str, mean_latency: float = 0.0,
                            python: str | None = None) -> str:
    """Build a tar.gz containing SEVERAL launcher scripts — the shape
    of multi-daemon systems (tidb's pd/tikv/tidb triple, mysql
    cluster's mgmd/ndbd/mysqld roles). `binaries` maps binary name ->
    jepsen_tpu.dbs module; every script shares the same state file so
    the role daemons and the SQL daemon see one cluster."""
    import tarfile

    python = python or sys.executable
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    os.makedirs(os.path.dirname(os.path.abspath(dest)) or ".", exist_ok=True)
    with tempfile.TemporaryDirectory() as td:
        top = os.path.join(td, arcname)
        os.makedirs(top)
        for binary, module in binaries.items():
            script = (
                "#!/bin/bash\n"
                f"export PYTHONPATH={shlex.quote(repo_root)}:$PYTHONPATH\n"
                f"exec {shlex.quote(python)} -m {module} "
                f"--data {shlex.quote(data_path)} "
                f"--mean-latency {mean_latency} "
                "\"$@\"\n"
            )
            bin_path = os.path.join(top, binary)
            with open(bin_path, "w") as f:
                f.write(script)
            os.chmod(bin_path, 0o755)
        with tarfile.open(dest, "w:gz") as tar:
            tar.add(top, arcname=arcname)
    return dest
