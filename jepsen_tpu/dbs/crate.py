"""CrateDB test suite: optimistic-concurrency workloads over the HTTP
_sql endpoint using Crate's implicit `_version` MVCC column (reference:
/root/reference/crate/src/jepsen/crate/{core,lost_updates,
version_divergence}.clj — the reference drives Crate's shaded-postgres
JDBC; this speaks the HTTP _sql API, Crate's other first-class client
surface).

Workloads:
  - version-divergence: registers read as (value, _version); the
    multiversion checker demands every _version maps to exactly ONE
    value across all reads (version_divergence.clj:98-115)
  - lost-updates: per-key element sets grown by read + write-back
    guarded by `where _version = ?` — a lost update drops an
    acknowledged element (lost_updates.clj:1-148)

The hermetic backend is crate_sim: the shared mini SQL engine behind a
tiny HTTP _sql wrapper, with `_version` managed by the engine.
"""

from __future__ import annotations

import itertools
import json
import logging
import random
import socket
import time
import urllib.error
import urllib.request

from .. import checker as checker_mod
from . import common as cmn
from .. import cli, client, generator as gen, independent
from .. import osdist
from ..checker import Checker
from ..history import Op, ops as _ops
from .common import ArchiveDB, SuiteCfg, ready_gated_final, \
    once as _once, shared_flag as _shared_flag
# shared with the elasticsearch suite — identical workload shape and
# anomaly definition (no circular import: elasticsearch doesn't import
# crate)
from .elasticsearch import DirtyReadChecker as _EsDirtyReadChecker
from .elasticsearch import dirty_rw_gen as _es_dirty_rw_gen

log = logging.getLogger("jepsen_tpu.dbs.crate")

PORT = 4200
RETRIES = 16


_suite = SuiteCfg("crate", PORT, "/opt/crate")
node_host = _suite.host
node_port = _suite.port


class CrateDB(ArchiveDB):
    """Tarball install + daemon (crate/core.clj:278-336). Daemon args
    use real CrateDB's -C settings syntax (the sim accepts them too)."""

    binary = "crate"
    log_name = "crate.log"
    pid_name = "crate.pid"

    def __init__(self, archive_url: str | None = None,
                 ready_timeout: float = 60.0):
        super().__init__(_suite, archive_url, ready_timeout)

    def daemon_args(self, test, node) -> list:
        return [f"-Chttp.port={node_port(test, node)}",
                f"-Cnode.name={node}",
                "-Cnetwork.host=0.0.0.0"]

    def probe_ready(self, test, node) -> bool:
        conn = CrateConn(node_host(test, node), node_port(test, node),
                         timeout=2.0)
        try:
            conn.sql("select 1")
            return True
        except CrateError:
            return False


class CrateError(Exception):
    def __init__(self, message: str, code: int | None = None):
        super().__init__(message)
        self.code = code


class CrateConn:
    """HTTP _sql endpoint: POST {"stmt": ...} -> {cols, rows,
    rowcount}."""

    def __init__(self, host: str, port: int, timeout: float = 5.0):
        self.base = f"http://{host}:{port}/_sql"
        self.timeout = timeout

    def sql(self, stmt: str) -> dict:
        req = urllib.request.Request(
            self.base, data=json.dumps({"stmt": stmt}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return json.load(resp)
        except urllib.error.HTTPError as e:
            try:
                body = json.load(e)
            except (json.JSONDecodeError, ValueError):
                raise CrateError(f"HTTP {e.code}") from e
            err = body.get("error") or {}
            raise CrateError(err.get("message", str(body)),
                             err.get("code")) from e


def _ensure_version_column(conn, table: str) -> None:
    """Real CrateDB has an implicit _version system column on every
    table; the sim's engine materializes one on request. Best-effort:
    real Crate rejects the alter, which is fine."""
    try:
        conn.sql(f"alter table {table} add _version")
    except CrateError:
        pass


class VersionRegisterClient(client.Client):
    """Registers read with their _version (version_divergence.clj:
    50-92): read → (value, _version) tuple per key; write → upsert."""

    def __init__(self, conn=None, flag=None):
        self.conn = conn
        self.flag = flag or _shared_flag()

    def open(self, test, node):
        conn = CrateConn(node_host(test, node), node_port(test, node))
        me = VersionRegisterClient(conn, self.flag)

        def create():
            conn.sql("drop table if exists registers")
            conn.sql("create table registers (id int primary key, "
                     "value int)")
            _ensure_version_column(conn, "registers")

        _once(self.flag, create)
        return me

    def invoke(self, test, op: Op) -> Op:
        k, v = op.value
        try:
            if op.f == "read":
                res = self.conn.sql(
                    f"select value, _version from registers where id = {k}")
                if not res["rows"]:
                    return op.with_(
                        type="ok",
                        value=independent.tuple_(k, (None, None)))
                value, version = res["rows"][0]
                return op.with_(
                    type="ok",
                    value=independent.tuple_(
                        k, (int(value) if value is not None else None,
                            int(version))))
            if op.f == "write":
                n = self.conn.sql(
                    f"update registers set value = {v} where id = {k}"
                )["rowcount"]
                if n == 0:
                    try:
                        self.conn.sql(
                            f"insert into registers (id, value) "
                            f"values ({k}, {v})")
                    except CrateError as e:
                        if "duplicate" not in str(e).lower():
                            raise
                        self.conn.sql(
                            f"update registers set value = {v} "
                            f"where id = {k}")
                return op.with_(type="ok")
            raise ValueError(f"unknown op {op.f!r}")
        except CrateError as e:
            if "no master" in str(e):
                return op.with_(type="fail", error="no-master")
            crash = "fail" if op.f == "read" else "info"
            return op.with_(type=crash, error=str(e))
        except (socket.timeout, TimeoutError, OSError) as e:
            crash = "fail" if op.f == "read" else "info"
            return op.with_(type=crash, error=str(e))

    def close(self, test):
        pass


class MultiversionChecker(Checker):
    """Every observed _version must map to exactly one value
    (version_divergence.clj:94-115)."""

    def check(self, test, history, opts=None) -> dict:
        by_version: dict = {}
        for o in _ops(history):
            if not (o.is_ok and o.f == "read"):
                continue
            k, (value, version) = o.value
            if version is None:
                continue
            by_version.setdefault((k, version), set()).add(value)
        multis = {str(kv): sorted(vs, key=str)
                  for kv, vs in by_version.items() if len(vs) > 1}
        return {"valid": not multis, "multis": multis}


class LostUpdatesClient(client.Client):
    """Per-key element sets stored as comma-joined strings, grown with
    an optimistic `where _version = ?` write-back loop
    (lost_updates.clj:32-120). Version conflicts retry; exhausting
    retries is a definite :fail."""

    def __init__(self, conn=None, flag=None):
        self.conn = conn
        self.flag = flag or _shared_flag()

    def open(self, test, node):
        conn = CrateConn(node_host(test, node), node_port(test, node))
        me = LostUpdatesClient(conn, self.flag)

        def create():
            conn.sql("drop table if exists sets")
            conn.sql("create table sets (id int primary key, "
                     "elements string)")
            _ensure_version_column(conn, "sets")

        _once(self.flag, create)
        return me

    def invoke(self, test, op: Op) -> Op:
        k, v = op.value
        try:
            if op.f == "add":
                for _ in range(RETRIES):
                    res = self.conn.sql(
                        f"select elements, _version from sets "
                        f"where id = {k}")
                    if not res["rows"]:
                        try:
                            self.conn.sql(
                                f"insert into sets (id, elements) "
                                f"values ({k}, '{v}')")
                            return op.with_(type="ok")
                        except CrateError as e:
                            if "duplicate" in str(e).lower():
                                continue  # raced the insert; retry
                            raise
                    elements, version = res["rows"][0]
                    new = f"{elements},{v}" if elements else str(v)
                    n = self.conn.sql(
                        f"update sets set elements = '{new}' "
                        f"where id = {k} and _version = {int(version)}"
                    )["rowcount"]
                    if n == 1:
                        return op.with_(type="ok")
                return op.with_(type="fail", error="retries-exhausted")
            if op.f == "read":
                res = self.conn.sql(
                    f"select elements from sets where id = {k}")
                elements = (res["rows"][0][0] or "") if res["rows"] else ""
                values = sorted(int(x) for x in elements.split(",") if x)
                return op.with_(type="ok",
                                value=independent.tuple_(k, values))
            raise ValueError(f"unknown op {op.f!r}")
        except CrateError as e:
            crash = "fail" if op.f == "read" else "info"
            return op.with_(type=crash, error=str(e))
        except (socket.timeout, TimeoutError, OSError) as e:
            crash = "fail" if op.f == "read" else "info"
            return op.with_(type=crash, error=str(e))

    def close(self, test):
        pass


class DirtyReadClient(client.Client):
    """crate/dirty_read.clj:40-95: writes insert ids; reads probe a
    specific id (:ok iff present); refresh flushes the table; the
    strong read selects every id. Checked with the shared dirty-read
    set algebra (same anomaly family as the elasticsearch workload)."""

    def __init__(self, conn=None, flag=None):
        self.conn = conn
        self.flag = flag or _shared_flag()

    def open(self, test, node):
        conn = CrateConn(node_host(test, node), node_port(test, node))
        me = DirtyReadClient(conn, self.flag)

        def create():
            conn.sql("drop table if exists dirty_read")
            conn.sql("create table dirty_read (id int primary key)")

        _once(self.flag, create)
        return me

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "write":
                self.conn.sql(
                    f"insert into dirty_read (id) values ({op.value})")
                return op.with_(type="ok")
            if op.f == "read":
                rows = self.conn.sql(
                    f"select id from dirty_read where id = {op.value}"
                )["rows"]
                return op.with_(type="ok" if rows else "fail")
            if op.f == "refresh":
                try:
                    self.conn.sql("refresh table dirty_read")
                except CrateError as e:
                    # the sim's engine doesn't know the statement (no
                    # refresh lag there); any OTHER failure is real and
                    # must not masquerade as a successful refresh
                    if "can't parse statement" not in str(e):
                        return op.with_(type="fail", error=str(e))
                return op.with_(type="ok")
            if op.f == "strong-read":
                ids = sorted(int(r[0]) for r in self.conn.sql(
                    "select id from dirty_read")["rows"])
                return op.with_(type="ok", value=ids)
            raise ValueError(f"unknown op {op.f!r}")
        except (CrateError, socket.timeout, TimeoutError, OSError) as e:
            crash = "info" if op.f == "write" else "fail"
            return op.with_(type=crash, error=str(e))

    def close(self, test):
        pass


def workloads(opts: dict | None = None) -> dict:

    opts = opts or {}
    n_keys = opts.get("keys", 4)
    ops_per_key = opts.get("ops_per_key", 30)

    def vd_r(test, process):
        return {"type": "invoke", "f": "read", "value": None}

    def vd_w(test, process):
        return {"type": "invoke", "f": "write", "value": random.randrange(5)}

    counter = itertools.count()

    return {
        "version-divergence": {
            "client": VersionRegisterClient(),
            "during": independent.concurrent_generator(
                2, itertools.count(),
                lambda k: gen.limit(40, gen.stagger(
                    0.05, gen.mix([vd_r, vd_w])))),
            "checker": checker_mod.compose({
                "perf": checker_mod.perf_checker(),
                "multiversion": MultiversionChecker(),
            }),
        },
        "dirty-read": {
            "client": DirtyReadClient(),
            "during": gen.stagger(
                0.02, _es_dirty_rw_gen()),
            "final": gen.each(lambda: gen.seq([
                gen.once({"type": "invoke", "f": "refresh"}),
                gen.once({"type": "invoke", "f": "strong-read"}),
            ])),
            "checker": checker_mod.compose({
                "perf": checker_mod.perf_checker(),
                "dirty-read": _EsDirtyReadChecker(),
            }),
        },
        "lost-updates": {
            "client": LostUpdatesClient(),
            # a FIXED key set so the final phase can read every key
            "during": independent.concurrent_generator(
                2, iter(range(n_keys)),
                lambda k: gen.limit(
                    ops_per_key,
                    gen.stagger(
                        0.05,
                        lambda t, p: {"type": "invoke", "f": "add",
                                      "value": next(counter)}))),
            "final": gen.seq([
                {"type": "invoke", "f": "read",
                 "value": independent.tuple_(k, None)}
                for k in range(n_keys)
            ]),
            "checker": checker_mod.compose({
                "perf": checker_mod.perf_checker(),
                "sets": independent.checker(checker_mod.set_checker()),
            }),
        },
    }


def crate_test(opts: dict) -> dict:
    from ..testlib import noop_test

    wl = workloads(opts)[opts.get("workload", "version-divergence")]
    db_ = CrateDB(archive_url=opts.get("archive_url"))
    generator = gen.time_limit(
        opts.get("time_limit", 60),
        gen.nemesis(gen.start_stop(10, 10), wl["during"]),
    )
    if wl.get("final") is not None:
        generator = gen.phases(
            generator,
            gen.nemesis(gen.once({"type": "info", "f": "stop"})),
            gen.sleep(opts.get("quiesce", 10)),
            ready_gated_final(db_, gen.clients(wl["final"]), opts),
        )
    test = noop_test()
    test.update(opts)
    test.update(
        {
            "name": f"crate {opts.get('workload', 'version-divergence')}",
            "os": osdist.debian,
            "db": db_,
            "client": wl["client"],
            "nemesis": cmn.pick_nemesis(db_, opts),
            "generator": generator,
            "checker": wl["checker"],
        }
    )
    return test


def _opt_spec(p) -> None:
    cmn.nemesis_opt(p)
    p.add_argument("--workload", default="version-divergence",
                   choices=sorted(workloads().keys()))
    p.add_argument("--archive-url", dest="archive_url", default=None)


def main(argv=None) -> None:
    cli.main(
        {**cli.single_test_cmd(crate_test, opt_spec=_opt_spec),
         **cli.serve_cmd()},
        argv,
    )


if __name__ == "__main__":
    main()
