"""MongoDB test suites: document-level compare-and-set against a
replica set, in two flavors matching the reference's pair of suites —
mongodb-rocks (mongod on the RocksDB storage engine,
/root/reference/mongodb-rocks/src/jepsen/mongodb_rocks.clj) and
mongodb-smartos (mongod provisioned on SmartOS,
/root/reference/mongodb-smartos/src/jepsen/mongodb_smartos/
{core,document_cas,transfer}.clj).

Workloads:
  - document-cas: one document's `value` field as a register
    (document_cas.clj:40-95): read = find by _id (primary read
    preference); write = update-by-id asserting n==1; cas = conditional
    update, n==0 → :fail. Reads are idempotent → indeterminate reads
    remap to :fail; writes/cas keep :info (with-errors op #{:read}).
  - transfer: bank transfers across two documents WITHOUT multi-doc
    transactions — the point of transfer.clj is that mongo (of this
    era) loses money under faults; the bank totals checker reports it.

Write concern is an option ("majority" by default, the reference's
safest mode).
"""

from __future__ import annotations

import itertools
import logging
import random
import socket
import time

from .. import checker as checker_mod
from .. import cli, client, generator as gen, models, osdist
from ..checker import Checker
from ..history import Op, ops as _ops
from . import mongo_proto
from .common import ArchiveDB, SuiteCfg, once, shared_flag
from . import common as cmn

log = logging.getLogger("jepsen_tpu.dbs.mongodb")

PORT = 27017
DB_NAME = "jepsen"
COLL = "jepsen"
REG_ID = 0


_suite = SuiteCfg("mongodb", PORT, "/opt/mongodb")
node_host = _suite.host
node_port = _suite.port


class MongoDB(ArchiveDB):
    """mongod per node as one replica set; the primary issues
    replSetInitiate once members answer (core.clj:40-130's install/
    configure/start + replica-set bring-up)."""

    binary = "mongod"
    log_name = "mongod.log"
    pid_name = "mongod.pid"

    def __init__(self, archive_url: str | None = None,
                 storage_engine: str | None = None,
                 ready_timeout: float = 60.0):
        super().__init__(_suite, archive_url, ready_timeout)
        self.storage_engine = storage_engine

    def daemon_args(self, test, node) -> list:
        d = _suite.dir(test, node)
        args = ["--replSet", "jepsen",
                "--dbpath", f"{d}/data",
                "--bind_ip", "0.0.0.0",
                "--port", str(node_port(test, node))]
        if self.storage_engine:
            # mongodb-rocks: mongod --storageEngine rocksdb
            args += ["--storageEngine", self.storage_engine]
        return args

    def probe_ready(self, test, node) -> bool:
        conn = mongo_proto.MongoConn(
            node_host(test, node), node_port(test, node),
            timeout=2.0, connect_timeout=2.0)
        try:
            conn.command("admin", {"ping": 1})
            return True
        except mongo_proto.MongoError:
            return False
        finally:
            conn.close()

    def post_start(self, test, node) -> None:
        if node != test["nodes"][0]:
            return
        members = [
            {"_id": i, "host": f"{node_host(test, n)}:"
                               f"{node_port(test, n)}"}
            for i, n in enumerate(test["nodes"])
        ]
        conn = mongo_proto.MongoConn(
            node_host(test, node), node_port(test, node))
        try:
            conn.command("admin", {
                "replSetInitiate": {"_id": "jepsen",
                                    "members": members}})
        except mongo_proto.MongoError as e:
            if "already initialized" not in str(e):
                raise
        finally:
            conn.close()


class DocumentCasClient(client.Client):
    """Register on one document (document_cas.clj:40-95)."""

    def __init__(self, write_concern: str = "majority", conn=None,
                 flag=None):
        self.write_concern = write_concern
        self.conn = conn
        self.flag = flag or shared_flag()

    def open(self, test, node):
        conn = mongo_proto.MongoConn(node_host(test, node),
                                     node_port(test, node))
        me = DocumentCasClient(self.write_concern, conn, self.flag)
        once(self.flag, lambda: conn.update(
            DB_NAME, COLL, {"_id": REG_ID},
            {"_id": REG_ID, "value": None}, upsert=True,
            w=self.write_concern))
        return me

    def invoke(self, test, op: Op) -> Op:
        try:
            out = self._invoke(op)
        except (mongo_proto.MongoError, socket.timeout, TimeoutError,
                ConnectionError, OSError) as e:
            out = op.with_(type="info", error=str(e))
        # reads are idempotent: indeterminate reads remap to :fail
        # (with-errors op #{:read}, core.clj's error macro)
        if op.f == "read" and out.type == "info":
            out = out.with_(type="fail")
        return out

    def _invoke(self, op: Op) -> Op:
        if op.f == "read":
            doc = self.conn.find_one(DB_NAME, COLL, {"_id": REG_ID})
            return op.with_(type="ok",
                            value=doc["value"] if doc else None)
        if op.f == "write":
            res = self.conn.update(
                DB_NAME, COLL, {"_id": REG_ID},
                {"_id": REG_ID, "value": op.value},
                w=self.write_concern)
            if res.get("n") != 1:
                return op.with_(type="info", error=f"n={res.get('n')}")
            return op.with_(type="ok")
        if op.f == "cas":
            old, new = op.value
            res = self.conn.update(
                DB_NAME, COLL, {"_id": REG_ID, "value": old},
                {"_id": REG_ID, "value": new},
                w=self.write_concern)
            n = res.get("n", 0)
            if n == 0:
                return op.with_(type="fail")
            if n == 1:
                return op.with_(type="ok")
            raise mongo_proto.MongoError(
                {"errmsg": f"CAS modified {n} documents"})
        raise ValueError(f"unknown op {op.f!r}")

    def close(self, test):
        if self.conn:
            self.conn.close()


class LoggerClient(client.Client):
    """The mongodb-rocks logger/queue perf client
    (mongodb_rocks.clj:85-134): write = insert a timestamped document
    keyed by the generator's unique id; delete = findAndModify-remove
    the OLDEST document (sort time ascending). No linearizability
    model — the workload exists to hammer the storage engine and plot
    latency (checker = perf only, :157-168)."""

    def __init__(self, conn=None, payload_bytes: int = 64):
        self.conn = conn
        self.payload = "x" * payload_bytes

    def open(self, test, node):
        conn = mongo_proto.MongoConn(node_host(test, node),
                                     node_port(test, node))
        return LoggerClient(conn, len(self.payload))

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "write":
                res = self.conn.insert(
                    DB_NAME, "logger",
                    [{"_id": op.value,
                      "time": int(time.time() * 1000),
                      "payload": self.payload}],
                    w="acknowledged")
                if res.get("writeErrors"):
                    # a server-side write error is a DEFINITE
                    # non-application (e.g. duplicate _id), not an
                    # indeterminate timeout
                    return op.with_(type="fail",
                                    error=str(res["writeErrors"][0]))
                return op.with_(type="ok")
            if op.f == "delete":
                res = self.conn.find_and_modify(
                    DB_NAME, "logger", query={}, sort={"time": 1},
                    remove=True)
                doc = res.get("value")
                if doc is None:
                    return op.with_(type="fail")
                return op.with_(type="ok", value=doc.get("_id"))
            raise ValueError(f"unknown op {op.f!r}")
        except (mongo_proto.MongoError, socket.timeout, TimeoutError,
                ConnectionError, OSError) as e:
            return op.with_(type="info", error=str(e))

    def close(self, test):
        if self.conn:
            self.conn.close()


def logger_write(test, process):
    # timestamped unique id, the reference's "<epoch>-oempa_<rand>"
    return {"type": "invoke", "f": "write",
            "value": f"{int(time.time())}-oempa_{random.randrange(2**31)}"}


def logger_delete(test, process):
    return {"type": "invoke", "f": "delete", "value": None}


class TransferClient(client.Client):
    """Bank transfers across account documents WITHOUT transactions
    (transfer.clj:1-281): read each balance, conditionally CAS each
    document — partial failures lose or invent money, which the totals
    checker surfaces."""

    def __init__(self, n: int = 4, starting_balance: int = 10,
                 write_concern: str = "majority", conn=None, flag=None):
        self.n = n
        self.starting_balance = starting_balance
        self.write_concern = write_concern
        self.conn = conn
        self.flag = flag or shared_flag()

    def open(self, test, node):
        conn = mongo_proto.MongoConn(node_host(test, node),
                                     node_port(test, node))
        me = TransferClient(self.n, self.starting_balance,
                            self.write_concern, conn, self.flag)

        def create():
            for i in range(self.n):
                conn.update(DB_NAME, "accounts", {"_id": i},
                            {"_id": i, "balance": self.starting_balance},
                            upsert=True, w=self.write_concern)

        once(self.flag, create)
        return me

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "read":
                docs = self.conn.find_all(DB_NAME, "accounts")
                return op.with_(type="ok",
                                value={d["_id"]: d["balance"]
                                       for d in docs})
            if op.f == "transfer":
                frm, to = op.value["from"], op.value["to"]
                amount = op.value["amount"]
                a = self.conn.find_one(DB_NAME, "accounts", {"_id": frm})
                b = self.conn.find_one(DB_NAME, "accounts", {"_id": to})
                if a is None or b is None:
                    return op.with_(type="fail", error="missing-account")
                if a["balance"] < amount:
                    return op.with_(type="fail", error="insufficient")
                # two independent CAS writes — no transaction
                r1 = self.conn.update(
                    DB_NAME, "accounts",
                    {"_id": frm, "balance": a["balance"]},
                    {"_id": frm, "balance": a["balance"] - amount},
                    w=self.write_concern)
                if r1.get("n") != 1:
                    return op.with_(type="fail", error="cas-from")
                r2 = self.conn.update(
                    DB_NAME, "accounts",
                    {"_id": to, "balance": b["balance"]},
                    {"_id": to, "balance": b["balance"] + amount},
                    w=self.write_concern)
                if r2.get("n") != 1:
                    # money already left `from`: indeterminate overall
                    return op.with_(type="info", error="cas-to")
                return op.with_(type="ok")
            raise ValueError(f"unknown op {op.f!r}")
        except (mongo_proto.MongoError, socket.timeout, TimeoutError,
                ConnectionError, OSError) as e:
            crash = "fail" if op.f == "read" else "info"
            return op.with_(type=crash, error=str(e))

    def close(self, test):
        if self.conn:
            self.conn.close()


class TransferTotalsChecker(Checker):
    """Totals must be conserved — the transfer workload exists to show
    they are not under faults (transfer.clj's checker)."""

    def __init__(self, total: int):
        self.total = total

    def check(self, test, history, opts=None) -> dict:
        bad = [o.to_dict() for o in _ops(history)
               if o.is_ok and o.f == "read"
               and sum(o.value.values()) != self.total]
        return {"valid": not bad, "bad_reads": bad[:10]}


def r(test, process):
    return {"type": "invoke", "f": "read", "value": None}


def w(test, process):
    return {"type": "invoke", "f": "write", "value": random.randrange(5)}


def cas(test, process):
    return {"type": "invoke", "f": "cas",
            "value": (random.randrange(5), random.randrange(5))}


def transfer_gen(test, process):
    n = test.get("accounts_n", 4)
    frm, to = random.sample(range(n), 2)
    return {"type": "invoke", "f": "transfer",
            "value": {"from": frm, "to": to,
                      "amount": 1 + random.randrange(3)}}


def workloads(opts: dict) -> dict:
    wc = opts.get("write_concern", "majority")
    n = opts.get("accounts", 4)
    starting = opts.get("starting_balance", 10)
    mix = ([w, cas, cas] if opts.get("no_read") else [r, w, cas, cas])
    return {
        "document-cas": {
            "client": DocumentCasClient(wc),
            "during": gen.stagger(opts.get("stagger", 0.05),
                                  gen.mix(mix)),
            "model": models.CASRegister(),
            "checker": checker_mod.compose({
                "perf": checker_mod.perf_checker(),
                "linear": checker_mod.linearizable(),
            }),
        },
        "transfer": {
            "client": TransferClient(n, starting, wc),
            "during": gen.stagger(opts.get("stagger", 0.05),
                                  gen.mix([r, transfer_gen])),
            "checker": checker_mod.compose({
                "perf": checker_mod.perf_checker(),
                "totals": TransferTotalsChecker(n * starting),
            }),
            "test_opts": {"accounts_n": n},
        },
        # mongodb-rocks's logger-perf-test (mongodb_rocks.clj:157-168):
        # 2:1 timestamped inserts vs remove-oldest, latency plots only
        "logger-perf": {
            "client": LoggerClient(),
            "during": gen.stagger(
                opts.get("stagger", 0.01),
                gen.mix([logger_write, logger_write, logger_delete])),
            "checker": checker_mod.compose({
                "perf": checker_mod.perf_checker(),
            }),
        },
    }


def mongodb_test(opts: dict) -> dict:
    from ..testlib import noop_test

    wl = workloads(opts)[opts.get("workload", "document-cas")]
    flavor = opts.get("flavor", "rocks")
    db_ = MongoDB(
        archive_url=opts.get("archive_url"),
        storage_engine="rocksdb" if flavor == "rocks" else None)
    test = noop_test()
    test.update(opts)
    test.update(
        {
            "name": f"mongodb-{flavor} {opts.get('workload', 'document-cas')}",
            # mongodb-smartos runs on SmartOS; rocks on debian
            "os": osdist.smartos if flavor == "smartos" else osdist.debian,
            "db": db_,
            "client": wl["client"],
            "model": wl.get("model"),
            "generator": wl["during"],
            "checker": wl["checker"],
        }
    )
    # MongoDB inherits kill/pause from ArchiveDB, so composed fault
    # packages ("--nemesis kill,partition") work out of the box; plain
    # registry names fall through to pick_nemesis as before.
    if not cmn.fault_package_wiring(
            test, db_, opts,
            stability_generator=wl["during"],
            corrupt_paths=opts.get("corrupt_paths")
            or [lambda t, n: f"{db_.suite.dir(t, n)}/{db_.log_name}"]):
        test.update({
            "nemesis": cmn.pick_nemesis(db_, opts),
            "generator": gen.time_limit(
                opts.get("time_limit", 60),
                gen.nemesis(
                    gen.start_stop(10, 10),
                    wl["during"],
                ),
            ),
        })
    test.update(wl.get("test_opts") or {})
    return test


def mongodb_rocks_test(opts: dict) -> dict:
    """mongodb_rocks.clj — document CAS on the RocksDB engine."""
    return mongodb_test({**opts, "flavor": "rocks"})


def mongodb_smartos_test(opts: dict) -> dict:
    """mongodb_smartos — the same suite provisioned on SmartOS."""
    return mongodb_test({**opts, "flavor": "smartos"})


def _opt_spec(p) -> None:
    cmn.nemesis_opt(p)
    p.add_argument("--workload", default="document-cas",
                   choices=["document-cas", "transfer", "logger-perf"])
    p.add_argument("--archive-url", dest="archive_url", default=None)
    p.add_argument("--flavor", default="rocks",
                   choices=["rocks", "smartos"])
    p.add_argument("--write-concern", dest="write_concern",
                   default="majority")
    p.add_argument("--no-read", dest="no_read", action="store_true",
                   help="document_cas.clj's no-read variant (mongo has "
                        "no linearizable reads)")


def main(argv=None) -> None:
    cli.main(
        {**cli.single_test_cmd(mongodb_test, opt_spec=_opt_spec),
         **cli.serve_cmd()},
        argv,
    )


if __name__ == "__main__":
    main()
