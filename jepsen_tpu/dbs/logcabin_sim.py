"""A hermetic LogCabin lookalike. LogCabin's client surface in the
reference suite is the ON-NODE `treeops` binary driven over SSH
(logcabin.clj:163-210) — so this sim ships an archive with two
programs:

  - logcabind: a placeholder daemon (binds its port so readiness and
    kill/restart nemeses have something real to act on)
  - treeops:   the CLI with the reference's exact contract:
                 treeops -c <servers> -q -t <s> read <path>
                 echo -n v  | treeops ... write <path>
                 echo -n v2 | treeops ... -p <path>:<v1> write <path>
               conditional writes print "Error: ... CAS failed ..." and
               exit nonzero on mismatch

The tree lives in the shared flock-guarded store, so every node's
treeops sees one linearizable namespace."""

from __future__ import annotations

import argparse
import os
import shlex
import socketserver
import sys
import tarfile
import tempfile

from .simbase import Store


# ---------------------------------------------------------------------------
# treeops CLI


def treeops_main(argv) -> int:
    p = argparse.ArgumentParser(prog="treeops", allow_abbrev=False)
    p.add_argument("-c", dest="cluster", default=None)
    p.add_argument("-q", action="store_true")
    p.add_argument("-t", dest="timeout", default=None)
    p.add_argument("-p", dest="predicate", default=None)
    p.add_argument("--data", required=True)
    p.add_argument("command", choices=["read", "write", "remove"])
    p.add_argument("path")
    args = p.parse_args(argv)
    store = Store(args.data)

    if args.command == "read":
        def read(data):
            return (data.get("tree") or {}).get(args.path), None

        value = store.transact(read)
        if value is None:
            print(f"Error: {args.path} does not exist", file=sys.stderr)
            return 1
        sys.stdout.write(value)
        return 0

    if args.command == "write":
        value = sys.stdin.read()
        want = None
        if args.predicate:
            pred_path, _, want = args.predicate.partition(":")
            if pred_path != args.path:
                print("Error: predicate path mismatch", file=sys.stderr)
                return 1

        def write(data):
            tree = dict(data.get("tree") or {})
            if want is not None and tree.get(args.path) != want:
                return False, None
            tree[args.path] = value
            new = dict(data)
            new["tree"] = tree
            return True, new

        if store.transact(write):
            return 0
        print("Error: CAS failed: content doesn't match", file=sys.stderr)
        return 1

    def remove(data):
        tree = dict(data.get("tree") or {})
        tree.pop(args.path, None)
        new = dict(data)
        new["tree"] = tree
        return None, new

    store.transact(remove)
    return 0


# ---------------------------------------------------------------------------
# placeholder daemon


class _Ping(socketserver.BaseRequestHandler):
    def handle(self):
        try:
            self.request.sendall(b"logcabin-sim\n")
        except OSError:
            pass


def serve(argv=None) -> None:
    p = argparse.ArgumentParser(description="logcabin daemon sim",
                                allow_abbrev=False)
    p.add_argument("--data", required=True)
    p.add_argument("--mean-latency", type=float, default=0.0)
    p.add_argument("--port", type=int, default=5254)
    p.add_argument("--name", default="sim")
    p.add_argument("--bootstrap", action="store_true")
    args = p.parse_args(sys.argv[1:] if argv is None else argv)
    srv = socketserver.ThreadingTCPServer(("127.0.0.1", args.port), _Ping)
    srv.allow_reuse_address = True
    srv.daemon_threads = True
    print(f"logcabin-sim {args.name} on {args.port}, data={args.data}")
    sys.stdout.flush()
    srv.serve_forever()


def build_archive(dest: str, data_path: str, python: str | None = None
                  ) -> str:
    """Archive with both logcabind and treeops launchers sharing one
    state file."""
    python = python or sys.executable
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    daemon = (
        "#!/bin/bash\n"
        f"export PYTHONPATH={shlex.quote(repo_root)}:$PYTHONPATH\n"
        f"exec {shlex.quote(python)} -m jepsen_tpu.dbs.logcabin_sim "
        f"--data {shlex.quote(data_path)} \"$@\"\n"
    )
    treeops = (
        "#!/bin/bash\n"
        f"export PYTHONPATH={shlex.quote(repo_root)}:$PYTHONPATH\n"
        f"exec {shlex.quote(python)} -c "
        "'import sys; from jepsen_tpu.dbs.logcabin_sim import "
        "treeops_main; sys.exit(treeops_main(sys.argv[1:]))' "
        f"--data {shlex.quote(data_path)} \"$@\"\n"
    )
    os.makedirs(os.path.dirname(os.path.abspath(dest)) or ".",
                exist_ok=True)
    with tempfile.TemporaryDirectory() as td:
        top = os.path.join(td, "logcabin-sim")
        os.makedirs(top)
        for name, script in (("logcabind", daemon), ("treeops", treeops)):
            path = os.path.join(top, name)
            with open(path, "w") as f:
                f.write(script)
            os.chmod(path, 0o755)
        with tarfile.open(dest, "w:gz") as tar:
            tar.add(top, arcname="logcabin-sim")
    return dest


if __name__ == "__main__":
    serve()
