"""Minimal RethinkDB client driver: the V0_4/JSON wire protocol and the
ReQL term builders the rethinkdb suite uses (reference:
rethinkdb/src/jepsen/rethinkdb/document_cas.clj drives the clojure
rethinkdb driver; this builds the same term trees by hand).

Wire: magic V0_4 (0x400c2d20) + authkey + JSON-protocol magic
(0x7e6970c7), then NUL-terminated "SUCCESS"; queries are
8-byte token + length + JSON [START, term, opts]; replies are
token + length + JSON {t: type, r: [...]}.
"""

from __future__ import annotations

import itertools
import json
import socket
import struct

V0_4 = 0x400C2D20
PROTOCOL_JSON = 0x7E6970C7

START = 1
SUCCESS_ATOM = 1
SUCCESS_SEQUENCE = 2
CLIENT_ERROR = 16
COMPILE_ERROR = 17
RUNTIME_ERROR = 18

# term types (ql2 protocol)
MAKE_ARRAY = 2
VAR = 10
ERROR = 12
DB = 14
TABLE = 15
GET = 16
EQ = 17
GET_FIELD = 31
UPDATE = 53
INSERT = 56
DB_CREATE = 57
TABLE_CREATE = 60
BRANCH = 65
FUNC = 69
DEFAULT = 92
RECONFIGURE = 176


class ReqlError(Exception):
    def __init__(self, rtype: int, message: str):
        super().__init__(message)
        self.rtype = rtype


def datum(v):
    """Literal values; arrays must become MAKE_ARRAY terms."""
    if isinstance(v, (list, tuple)):
        return [MAKE_ARRAY, [datum(x) for x in v]]
    if isinstance(v, dict):
        return {k: datum(x) for k, x in v.items()}
    return v


def db(name):
    return [DB, [name]]


def table(db_term, name, read_mode=None):
    opts = {"read_mode": read_mode} if read_mode else {}
    return [TABLE, [db_term, name], opts] if opts else [TABLE,
                                                        [db_term, name]]


def get(table_term, key):
    return [GET, [table_term, key]]


def insert(table_term, doc, conflict=None):
    opts = {"conflict": conflict} if conflict else {}
    args = [table_term, datum(doc)]
    return [INSERT, args, opts] if opts else [INSERT, args]


def update(sel_term, patch_or_func):
    return [UPDATE, [sel_term, patch_or_func]]


def get_field(term, field):
    return [GET_FIELD, [term, field]]


def eq(a, b):
    return [EQ, [a, b]]


def branch(cond, then, otherwise):
    return [BRANCH, [cond, datum(then), otherwise]]


def error(msg):
    return [ERROR, [msg]]


def func(param_id, body):
    return [FUNC, [[MAKE_ARRAY, [param_id]], body]]


def var(param_id):
    return [VAR, [param_id]]


def default(term, fallback):
    return [DEFAULT, [term, fallback]]


def db_create(name):
    return [DB_CREATE, [name]]


def table_create(db_term, name, replicas=None):
    opts = {"replicas": replicas} if replicas else {}
    return ([TABLE_CREATE, [db_term, name], opts] if opts
            else [TABLE_CREATE, [db_term, name]])


def reconfigure(table_term, shards: int, replicas: dict,
                primary_replica_tag: str):
    """r.table(...).reconfigure({shards, replicas: {tag: n...},
    primary_replica_tag}) — the topology-change call the reconfigure
    nemesis drives (rethinkdb.clj:180-194)."""
    return [RECONFIGURE, [table_term],
            {"shards": shards,
             "replicas": datum(replicas),
             "primary_replica_tag": primary_replica_tag}]


class ReqlConn:
    _tokens = itertools.count(1)

    def __init__(self, host: str, port: int, auth_key: str = "",
                 timeout: float = 5.0, connect_timeout: float = 10.0):
        self.sock = socket.create_connection((host, port),
                                             timeout=connect_timeout)
        self.sock.settimeout(timeout)
        key = auth_key.encode()
        self.sock.sendall(struct.pack("<I", V0_4)
                          + struct.pack("<I", len(key)) + key
                          + struct.pack("<I", PROTOCOL_JSON))
        greeting = b""
        while not greeting.endswith(b"\x00"):
            chunk = self.sock.recv(64)
            if not chunk:
                raise ConnectionError("rethinkdb handshake EOF")
            greeting += chunk
        if b"SUCCESS" not in greeting:
            raise ReqlError(CLIENT_ERROR, greeting.decode(errors="replace"))

    def _read_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("rethinkdb connection closed")
            buf += chunk
        return buf

    def run(self, term):
        """Run one term; returns the response payload (atom value, or a
        list for sequences)."""
        token = next(self._tokens)
        q = json.dumps([START, term, {}]).encode()
        self.sock.sendall(struct.pack("<q", token)
                          + struct.pack("<I", len(q)) + q)
        r_token = struct.unpack("<q", self._read_exact(8))[0]
        if r_token != token:
            raise ReqlError(CLIENT_ERROR, "token mismatch")
        (length,) = struct.unpack("<I", self._read_exact(4))
        resp = json.loads(self._read_exact(length))
        t = resp["t"]
        if t == SUCCESS_ATOM:
            return resp["r"][0]
        if t == SUCCESS_SEQUENCE:
            return resp["r"]
        raise ReqlError(t, str(resp.get("r")))

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass
