"""Minimal AMQP 0-9-1 client — the transport for the rabbitmq suite
(the reference rides langohr/the Java client, rabbitmq.clj:1-263).

Implemented subset: connection handshake (PLAIN auth), channel open,
queue declare/purge, publisher confirms (confirm.select + basic.ack
tracking), basic.publish (method + content header + body frames),
basic.get with auto-ack. Frames are type(1) channel(2) size(4) payload
0xCE; methods are class-id(2) method-id(2) + packed arguments."""

from __future__ import annotations

import socket
import struct

FRAME_METHOD = 1
FRAME_HEADER = 2
FRAME_BODY = 3
FRAME_END = 0xCE

# (class, method)
CONN_START = (10, 10)
CONN_START_OK = (10, 11)
CONN_TUNE = (10, 30)
CONN_TUNE_OK = (10, 31)
CONN_OPEN = (10, 40)
CONN_OPEN_OK = (10, 41)
CONN_CLOSE = (10, 50)
CH_OPEN = (20, 10)
CH_OPEN_OK = (20, 11)
Q_DECLARE = (50, 10)
Q_DECLARE_OK = (50, 11)
Q_PURGE = (50, 30)
Q_PURGE_OK = (50, 31)
BASIC_PUBLISH = (60, 40)
BASIC_GET = (60, 70)
BASIC_GET_OK = (60, 71)
BASIC_GET_EMPTY = (60, 72)
BASIC_ACK = (60, 80)
BASIC_REJECT = (60, 90)
CONFIRM_SELECT = (85, 10)
CONFIRM_SELECT_OK = (85, 11)


class AmqpError(Exception):
    pass


def shortstr(s: str) -> bytes:
    b = s.encode()
    return bytes([len(b)]) + b


def longstr(b: bytes) -> bytes:
    return struct.pack(">I", len(b)) + b


def read_shortstr(buf: bytes, pos: int) -> tuple:
    n = buf[pos]
    return buf[pos + 1:pos + 1 + n].decode(), pos + 1 + n


class AmqpConn:
    def __init__(self, host: str, port: int, user: str = "guest",
                 password: str = "guest", vhost: str = "/",
                 timeout: float = 5.0, connect_timeout: float = 10.0):
        self.sock = socket.create_connection((host, port),
                                             timeout=connect_timeout)
        self.sock.settimeout(timeout)
        self._handshake(user, password, vhost)
        self._channel_open = False
        self._confirms = False
        self._publish_seq = 0

    # -- framing ----------------------------------------------------------

    def _read_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("amqp connection closed")
            buf += chunk
        return buf

    def _read_frame(self) -> tuple:
        header = self._read_exact(7)
        ftype, channel, size = struct.unpack(">BHI", header)
        payload = self._read_exact(size)
        end = self._read_exact(1)
        if end[0] != FRAME_END:
            raise AmqpError("bad frame end")
        return ftype, channel, payload

    def _send_frame(self, ftype: int, channel: int,
                    payload: bytes) -> None:
        self.sock.sendall(struct.pack(">BHI", ftype, channel,
                                      len(payload))
                          + payload + bytes([FRAME_END]))

    def _send_method(self, channel: int, cm: tuple,
                     args: bytes = b"") -> None:
        self._send_frame(FRAME_METHOD, channel,
                         struct.pack(">HH", *cm) + args)

    def _expect_method(self, want: tuple) -> bytes:
        ftype, _ch, payload = self._read_frame()
        if ftype != FRAME_METHOD:
            raise AmqpError(f"expected method frame, got {ftype}")
        cm = struct.unpack_from(">HH", payload)
        if cm == CONN_CLOSE:
            code, = struct.unpack_from(">H", payload, 4)
            text, _ = read_shortstr(payload, 6)
            raise AmqpError(f"connection closed ({code}): {text}")
        if cm != want:
            raise AmqpError(f"expected {want}, got {cm}")
        return payload[4:]

    # -- connection -------------------------------------------------------

    def _handshake(self, user: str, password: str, vhost: str) -> None:
        self.sock.sendall(b"AMQP\x00\x00\x09\x01")
        self._expect_method(CONN_START)
        creds = b"\x00" + user.encode() + b"\x00" + password.encode()
        args = (struct.pack(">I", 0)          # empty client props table
                + shortstr("PLAIN") + longstr(creds) + shortstr("en_US"))
        self._send_method(0, CONN_START_OK, args)
        self._expect_method(CONN_TUNE)
        self._send_method(0, CONN_TUNE_OK,
                          struct.pack(">HIH", 0, 131072, 0))
        self._send_method(0, CONN_OPEN,
                          shortstr(vhost) + shortstr("") + b"\x00")
        self._expect_method(CONN_OPEN_OK)
        self._send_method(1, CH_OPEN, shortstr(""))
        self._expect_method(CH_OPEN_OK)
        self._channel_open = True

    # -- operations -------------------------------------------------------

    def queue_declare(self, queue: str, durable: bool = True) -> None:
        bits = 0x02 if durable else 0
        args = (struct.pack(">H", 0) + shortstr(queue) + bytes([bits])
                + struct.pack(">I", 0))
        self._send_method(1, Q_DECLARE, args)
        self._expect_method(Q_DECLARE_OK)

    def queue_purge(self, queue: str) -> int:
        args = struct.pack(">H", 0) + shortstr(queue) + b"\x00"
        self._send_method(1, Q_PURGE, args)
        payload = self._expect_method(Q_PURGE_OK)
        return struct.unpack_from(">I", payload)[0]

    def confirm_select(self) -> None:
        self._send_method(1, CONFIRM_SELECT, b"\x00")
        self._expect_method(CONFIRM_SELECT_OK)
        self._confirms = True

    def publish(self, queue: str, body: bytes,
                persistent: bool = True) -> bool:
        """Publish to the default exchange; with confirms enabled,
        True once the broker acks (rabbitmq.clj:155-164)."""
        args = (struct.pack(">H", 0) + shortstr("") + shortstr(queue)
                + b"\x00")
        self._send_method(1, BASIC_PUBLISH, args)
        # content header: class 60, weight 0, body size, flags
        flags = 0
        prop_payload = b""
        if persistent:
            flags |= 1 << 12                      # delivery-mode prop
            prop_payload = bytes([2])
        header = (struct.pack(">HHQ", 60, 0, len(body))
                  + struct.pack(">H", flags) + prop_payload)
        self._send_frame(FRAME_HEADER, 1, header)
        self._send_frame(FRAME_BODY, 1, body)
        if not self._confirms:
            return True
        self._publish_seq += 1
        payload = self._expect_method(BASIC_ACK)
        tag, = struct.unpack_from(">Q", payload)
        return tag >= self._publish_seq or bool(payload[8] & 1)

    def _basic_get(self, queue: str, no_ack: bool):
        """basic.get: (delivery_tag, body), or None when empty."""
        args = (struct.pack(">H", 0) + shortstr(queue)
                + (b"\x01" if no_ack else b"\x00"))
        self._send_method(1, BASIC_GET, args)
        ftype, _ch, payload = self._read_frame()
        cm = struct.unpack_from(">HH", payload)
        if cm == BASIC_GET_EMPTY:
            return None
        if cm != BASIC_GET_OK:
            raise AmqpError(f"unexpected get reply {cm}")
        tag, _redelivered = struct.unpack_from(">QB", payload, 4)
        ftype, _ch, header = self._read_frame()
        if ftype != FRAME_HEADER:
            raise AmqpError("expected content header")
        _cls, _weight, size = struct.unpack_from(">HHQ", header)
        body = b""
        while len(body) < size:
            ftype, _ch, chunk = self._read_frame()
            if ftype != FRAME_BODY:
                raise AmqpError("expected body frame")
            body += chunk
        return tag, body

    def get(self, queue: str):
        """Auto-ack basic.get: body bytes, or None when empty
        (langohr's lb/get, rabbitmq.clj:110)."""
        r = self._basic_get(queue, no_ack=True)
        return None if r is None else r[1]

    def get_unacked(self, queue: str):
        """basic.get WITHOUT auto-ack: (delivery_tag, body), or None
        when empty. The broker holds the message against this
        connection until it is acked/rejected — or the connection
        dies, at which point it requeues. This is the primitive under
        the distributed-semaphore pattern (rabbitmq.clj:185-226:
        holding the unacked delivery IS holding the mutex)."""
        return self._basic_get(queue, no_ack=False)

    def reject(self, delivery_tag: int, requeue: bool = True) -> None:
        """basic.reject (no -ok reply in AMQP 0-9-1): releases an
        unacked delivery, requeueing it when asked (lb/reject,
        rabbitmq.clj:250)."""
        args = struct.pack(">Q", delivery_tag) + bytes([1 if requeue
                                                        else 0])
        self._send_method(1, BASIC_REJECT, args)

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass
