"""Percona XtraDB Cluster test suite: bank, sets, and dirty-reads
workloads over the MySQL protocol (reference:
/root/reference/percona/src/jepsen/percona.clj:1-362 and
percona/dirty_reads.clj; clients live in mysql_common.py — Percona's
suite is the Galera pattern on Percona's distribution)."""

from __future__ import annotations

from .. import cli
from .mysql_common import make_sql_suite


def _daemon_args(suite, test, node) -> list:
    # the first node bootstraps a NEW cluster (empty gcomm://, the
    # --wsrep-new-cluster semantics of galera.clj:110-111); the rest
    # join it — without this a fresh real cluster can never form a
    # primary component
    primary = test["nodes"][0]
    gcomm = ("" if node == primary
             else ",".join(suite.host(test, n) for n in test["nodes"]
                           if n != node))
    return ["--port", str(suite.port(test, node)),
            f"--wsrep-cluster-address=gcomm://{gcomm}"]


suite, PerconaDB, workloads, percona_test, _opt_spec = make_sql_suite(
    "percona", 3306, "mysqld", _daemon_args,
    ("bank", "sets", "dirty-reads"))


def main(argv=None) -> None:
    cli.main(
        {**cli.single_test_cmd(percona_test, opt_spec=_opt_spec),
         **cli.serve_cmd()},
        argv,
    )


if __name__ == "__main__":
    main()
