"""A hermetic CrateDB lookalike: the HTTP _sql endpoint over the shared
mini SQL engine (crdb_sim.execute), with Crate's implicit `_version`
MVCC column managed by the engine. Every statement autocommits under
the shared flock (Crate has no multi-statement transactions — its
optimistic concurrency rides _version checks, which is exactly what the
crate suite exercises)."""

from __future__ import annotations

import argparse
import json
import random
import sys
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from . import crdb_sim
from .simbase import Store, build_sim_archive


class Handler(BaseHTTPRequestHandler):
    store: Store = None  # type: ignore[assignment]
    mean_latency: float = 0.0
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        sys.stdout.write("%s - %s\n" % (self.address_string(), fmt % args))
        sys.stdout.flush()

    def _reply(self, status: int, body: dict):
        payload = json.dumps(body).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def do_POST(self):
        if self.mean_latency > 0:
            time.sleep(random.expovariate(1.0 / self.mean_latency))
        length = int(self.headers.get("Content-Length") or 0)
        try:
            stmt = json.loads(self.rfile.read(length) or b"{}")["stmt"]
        except (json.JSONDecodeError, KeyError):
            return self._reply(400, {"error": {"message": "bad request"}})

        def run(data):
            try:
                cols, rows, tag = crdb_sim.execute(data, stmt)
            except crdb_sim.SqlError as e:
                return ("error", e), None
            rowcount = 0
            parts = tag.split()
            if parts and parts[-1].isdigit():
                rowcount = int(parts[-1])
            # pure reads don't rewrite the state file (a full json dump
            # under the global lock per SELECT would dominate latency)
            new = None if tag.startswith("SELECT") else data
            return ("ok", (cols, rows, rowcount)), new

        kind, payload = self.store.transact(run)
        if kind == "error":
            e = payload
            code = 4091 if e.sqlstate == "23505" else 5000
            return self._reply(409 if e.sqlstate == "23505" else 400, {
                "error": {"message": f"duplicate key: {e.message}"
                          if e.sqlstate == "23505" else e.message,
                          "code": code}})
        cols, rows, rowcount = payload
        self._reply(200, {"cols": cols, "rows": [list(r) for r in rows],
                          "rowcount": rowcount})


def parse_args(argv):
    p = argparse.ArgumentParser(description="crate _sql sim",
                                allow_abbrev=False)
    p.add_argument("--data", required=True)
    p.add_argument("--mean-latency", type=float, default=0.0)
    p.add_argument("--port", type=int, default=4200)
    p.add_argument("--name", default="sim")
    # real CrateDB's settings syntax: -Ckey=value (repeatable)
    p.add_argument("-C", action="append", default=[], dest="settings")
    return p.parse_args(argv)


def serve(argv=None) -> None:
    args = parse_args(sys.argv[1:] if argv is None else argv)
    settings = dict(s.split("=", 1) for s in args.settings if "=" in s)
    port = int(settings.get("http.port", args.port))
    name = settings.get("node.name", args.name)
    Handler.store = Store(args.data)
    Handler.mean_latency = args.mean_latency
    httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    print(f"crate-sim {name} serving on {port}, "
          f"data={args.data}")
    sys.stdout.flush()
    httpd.serve_forever()


def build_archive(dest: str, data_path: str, mean_latency: float = 0.0,
                  python: str | None = None) -> str:
    return build_sim_archive(
        dest, "jepsen_tpu.dbs.crate_sim", "crate", "crate-sim",
        data_path, mean_latency=mean_latency, python=python,
    )


if __name__ == "__main__":
    serve()
