"""A hermetic RobustIRC lookalike: the HTTP session API the robustirc
suite drives (robustirc.clj:102-135) — POST /robustirc/v1/session
creating {Sessionid, Sessionauth}, POST .../<sid>/message appending an
IRC line to the network-wide log (deduplicated by ClientMessageId,
RobustIRC's at-most-once contract), GET .../<sid>/messages returning
the whole log as a JSON array (the real server streams newline-JSON;
an array is the same payload without chunking)."""

from __future__ import annotations

import argparse
import json
import random
import secrets
import sys
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .simbase import Store, build_sim_archive

PREFIX = "/robustirc/v1"


class Handler(BaseHTTPRequestHandler):
    store: Store = None  # type: ignore[assignment]
    mean_latency: float = 0.0
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        sys.stdout.write("%s - %s\n" % (self.address_string(), fmt % args))
        sys.stdout.flush()

    def _reply(self, status: int, body) -> None:
        payload = (body if isinstance(body, bytes)
                   else json.dumps(body).encode())
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def _jitter(self):
        if self.mean_latency > 0:
            time.sleep(random.expovariate(1.0 / self.mean_latency))

    def do_POST(self):
        self._jitter()
        path = urllib.parse.urlparse(self.path).path
        if not path.startswith(PREFIX):
            return self._reply(404, {"error": "no route"})
        parts = [p for p in path[len(PREFIX):].split("/") if p]
        if parts == ["session"]:
            sid = secrets.token_hex(8)
            auth = secrets.token_hex(16)

            def create(data):
                sessions = dict(data.get("sessions") or {})
                sessions[sid] = auth
                new = dict(data)
                new["sessions"] = sessions
                return None, new

            self.store.transact(create)
            return self._reply(200, {"Sessionid": sid,
                                     "Sessionauth": auth,
                                     "Prefix": "robustirc-sim"})
        if len(parts) == 2 and parts[1] == "message":
            sid = parts[0]
            length = int(self.headers.get("Content-Length") or 0)
            try:
                body = json.loads(self.rfile.read(length))
            except json.JSONDecodeError:
                return self._reply(400, {"error": "bad json"})
            auth = self.headers.get("X-Session-Auth")

            def post(data):
                if (data.get("sessions") or {}).get(sid) != auth:
                    return 401, None
                msgs = list(data.get("messages") or [])
                mid = body.get("ClientMessageId")
                # at-most-once is scoped PER SESSION — different
                # clients may reuse ids
                if mid is not None and any(
                        m.get("ClientMessageId") == mid
                        and m.get("Session") == sid for m in msgs):
                    return 200, None  # duplicate
                msgs.append({"Id": {"Id": len(msgs)},
                             "Session": sid,
                             "Data": body.get("Data", ""),
                             "ClientMessageId": mid})
                new = dict(data)
                new["messages"] = msgs
                return 200, new

            status = self.store.transact(post)
            return self._reply(status, {} if status == 200
                               else {"error": "bad session"})
        self._reply(404, {"error": "no route"})

    def do_GET(self):
        self._jitter()
        path = urllib.parse.urlparse(self.path).path
        parts = [p for p in path[len(PREFIX):].split("/") if p]
        if len(parts) == 2 and parts[1] == "messages":
            sid = parts[0]
            auth = self.headers.get("X-Session-Auth")

            def read(data):
                if (data.get("sessions") or {}).get(sid) != auth:
                    return None, None
                return list(data.get("messages") or []), None

            msgs = self.store.transact(read)
            if msgs is None:
                return self._reply(401, {"error": "bad session"})
            return self._reply(200, msgs)
        self._reply(404, {"error": "no route"})


def parse_args(argv):
    p = argparse.ArgumentParser(description="robustirc sim",
                                allow_abbrev=False)
    p.add_argument("--data", required=True)
    p.add_argument("--mean-latency", type=float, default=0.0)
    p.add_argument("--port", type=int, default=13001)
    p.add_argument("--name", default="sim")
    p.add_argument("-network_name", default=None)  # tolerated
    p.add_argument("-peer_addr", default=None)
    return p.parse_args(argv)


def serve(argv=None) -> None:
    args = parse_args(sys.argv[1:] if argv is None else argv)
    Handler.store = Store(args.data)
    Handler.mean_latency = args.mean_latency
    httpd = ThreadingHTTPServer(("127.0.0.1", args.port), Handler)
    print(f"robustirc-sim {args.name} serving on {args.port}, "
          f"data={args.data}")
    sys.stdout.flush()
    httpd.serve_forever()


def build_archive(dest: str, data_path: str, mean_latency: float = 0.0,
                  python: str | None = None) -> str:
    return build_sim_archive(
        dest, "jepsen_tpu.dbs.robustirc_sim", "robustirc",
        "robustirc-sim", data_path, mean_latency=mean_latency,
        python=python,
    )


if __name__ == "__main__":
    serve()
