"""RethinkDB test suite: document-level compare-and-set over ReQL with
per-key independence (reference:
/root/reference/rethinkdb/src/jepsen/rethinkdb.clj and
rethinkdb/document_cas.clj:1-185).

The CAS is the reference's exact ReQL shape: an update whose FUNC body
branches on get_field equality and raises r.error("abort") otherwise —
verdict decided by the reply's replaced/errors counts
(document_cas.clj:93-107). Reads use get_field with a DEFAULT fallback
for missing documents; writes insert with conflict=update.
"""

from __future__ import annotations

import itertools
import logging
import random
import socket

from .. import checker as checker_mod
from .. import cli, client, generator as gen, independent, models
from .. import osdist
from ..history import Op
from . import rethink_proto as rp
from .common import ArchiveDB, SuiteCfg, once, shared_flag
from . import common as cmn

log = logging.getLogger("jepsen_tpu.dbs.rethinkdb")

PORT = 28015
DB_NAME = "jepsen"
TBL = "cas"


_suite = SuiteCfg("rethinkdb", PORT, "/opt/rethinkdb")
node_host = _suite.host
node_port = _suite.port


class RethinkDB(ArchiveDB):
    """rethinkdb daemon per node, joined to the primary
    (rethinkdb.clj's install/start — `rethinkdb --join primary:29015`)."""

    binary = "rethinkdb"
    log_name = "rethinkdb.log"
    pid_name = "rethinkdb.pid"

    def __init__(self, archive_url: str | None = None,
                 ready_timeout: float = 60.0):
        super().__init__(_suite, archive_url, ready_timeout)

    def daemon_args(self, test, node) -> list:
        d = _suite.dir(test, node)
        args = ["--driver-port", str(node_port(test, node)),
                "--directory", f"{d}/data"]
        primary = test["nodes"][0]
        if node != primary:
            args += ["--join", f"{node_host(test, primary)}:29015"]
        return args

    def probe_ready(self, test, node) -> bool:
        conn = rp.ReqlConn(node_host(test, node), node_port(test, node),
                           timeout=2.0, connect_timeout=2.0)
        conn.close()
        return True


class DocumentCasClient(client.Client):
    """Register per independent key (document_cas.clj:54-110). Reads
    are idempotent → indeterminate reads remap to :fail (with-errors op
    #{:read}); writes/cas stay :info on connection trouble."""

    def __init__(self, conn=None, flag=None, read_mode: str = "majority"):
        self.conn = conn
        self.flag = flag or shared_flag()
        self.read_mode = read_mode

    def open(self, test, node):
        conn = rp.ReqlConn(node_host(test, node), node_port(test, node))
        me = DocumentCasClient(conn, self.flag, self.read_mode)

        def create():
            conn.run(rp.db_create(DB_NAME))
            conn.run(rp.table_create(rp.db(DB_NAME), TBL,
                                     replicas=len(test["nodes"])))

        once(self.flag, create)
        return me

    def _table(self):
        return rp.table(rp.db(DB_NAME), TBL, read_mode=self.read_mode)

    def invoke(self, test, op: Op) -> Op:
        k, v = op.value
        try:
            out = self._invoke(k, v, op)
        except (rp.ReqlError, socket.timeout, TimeoutError,
                ConnectionError, OSError) as e:
            out = op.with_(type="info", error=str(e))
        if op.f == "read" and out.type == "info":
            out = out.with_(type="fail")
        return out

    def _invoke(self, k, v, op: Op) -> Op:
        row = rp.get(self._table(), k)
        if op.f == "read":
            value = self.conn.run(
                rp.default(rp.get_field(row, "val"), None))
            return op.with_(type="ok", value=independent.tuple_(k, value))
        if op.f == "write":
            res = self.conn.run(
                rp.insert(self._table(), {"id": k, "val": v},
                          conflict="update"))
            # an embedded write error (e.g. lost primary) arrives in a
            # SUCCESS_ATOM payload, not a RUNTIME_ERROR response
            if res.get("errors"):
                return op.with_(type="info",
                                error=res.get("first_error", "errors"))
            return op.with_(type="ok")
        if op.f == "cas":
            old, new = v
            res = self.conn.run(rp.update(
                row,
                rp.func(1, rp.branch(
                    rp.eq(rp.get_field(rp.var(1), "val"), old),
                    {"val": new},
                    rp.error("abort"),
                )),
            ))
            if res.get("errors") == 0 and res.get("replaced") == 1:
                return op.with_(type="ok")
            first_error = res.get("first_error", "")
            if res.get("errors") and "abort" not in first_error:
                # an infrastructure error (e.g. lost primary), not our
                # deliberate branch abort — the CAS may have applied
                return op.with_(type="info", error=first_error)
            return op.with_(type="fail")
        raise ValueError(f"unknown op {op.f!r}")

    def close(self, test):
        if self.conn:
            self.conn.close()


def r(test, process):
    return {"type": "invoke", "f": "read", "value": None}


def w(test, process):
    return {"type": "invoke", "f": "write", "value": random.randrange(5)}


def cas(test, process):
    return {"type": "invoke", "f": "cas",
            "value": (random.randrange(5), random.randrange(5))}


def rethinkdb_test(opts: dict) -> dict:
    from ..testlib import noop_test

    db_ = RethinkDB(archive_url=opts.get("archive_url"))
    test = noop_test()
    test.update(opts)
    test.update(
        {
            "name": "rethinkdb document-cas",
            "os": osdist.debian,
            "db": db_,
            "client": DocumentCasClient(
                read_mode=opts.get("read_mode", "majority")),
            "nemesis": cmn.pick_nemesis(db_, opts),
            "model": models.CASRegister(),
            "checker": checker_mod.compose({
                "perf": checker_mod.perf_checker(),
                "indep": independent.checker(checker_mod.compose({
                    "timeline": checker_mod.timeline_html(),
                    "linear": checker_mod.linearizable(),
                })),
            }),
            "generator": gen.time_limit(
                opts.get("time_limit", 60),
                gen.nemesis(
                    gen.start_stop(10, 10),
                    independent.concurrent_generator(
                        opts.get("threads_per_key", 2),
                        itertools.count(),
                        lambda k: gen.limit(
                            opts.get("ops_per_key", 50),
                            gen.stagger(opts.get("stagger", 0.05),
                                        gen.mix([r, w, cas])),
                        ),
                    ),
                ),
            ),
        }
    )
    return test


def _opt_spec(p) -> None:
    cmn.nemesis_opt(p)
    p.add_argument("--archive-url", dest="archive_url", default=None)
    p.add_argument("--read-mode", dest="read_mode", default="majority",
                   choices=["single", "majority", "outdated"])


def main(argv=None) -> None:
    cli.main(
        {**cli.single_test_cmd(rethinkdb_test, opt_spec=_opt_spec),
         **cli.serve_cmd()},
        argv,
    )


if __name__ == "__main__":
    main()
