"""RethinkDB test suite: document-level compare-and-set over ReQL with
per-key independence (reference:
/root/reference/rethinkdb/src/jepsen/rethinkdb.clj and
rethinkdb/document_cas.clj:1-185).

The CAS is the reference's exact ReQL shape: an update whose FUNC body
branches on get_field equality and raises r.error("abort") otherwise —
verdict decided by the reply's replaced/errors counts
(document_cas.clj:93-107). Reads use get_field with a DEFAULT fallback
for missing documents; writes insert with conflict=update.
"""

from __future__ import annotations

import itertools
import logging
import random
import socket

from .. import checker as checker_mod
from .. import cli, client, generator as gen, independent, models
from .. import nemesis as nemesis_mod
from .. import osdist
from ..history import Op
from . import rethink_proto as rp
from .common import ArchiveDB, SuiteCfg, once, shared_flag
from . import common as cmn

log = logging.getLogger("jepsen_tpu.dbs.rethinkdb")

PORT = 28015
DB_NAME = "jepsen"
TBL = "cas"


_suite = SuiteCfg("rethinkdb", PORT, "/opt/rethinkdb")
node_host = _suite.host
node_port = _suite.port


class RethinkDB(ArchiveDB):
    """rethinkdb daemon per node, joined to the primary
    (rethinkdb.clj's install/start — `rethinkdb --join primary:29015`)."""

    binary = "rethinkdb"
    log_name = "rethinkdb.log"
    pid_name = "rethinkdb.pid"

    def __init__(self, archive_url: str | None = None,
                 ready_timeout: float = 60.0):
        super().__init__(_suite, archive_url, ready_timeout)

    def daemon_args(self, test, node) -> list:
        d = _suite.dir(test, node)
        args = ["--driver-port", str(node_port(test, node)),
                "--directory", f"{d}/data"]
        primary = test["nodes"][0]
        if node != primary:
            args += ["--join", f"{node_host(test, primary)}:29015"]
        return args

    def probe_ready(self, test, node) -> bool:
        conn = rp.ReqlConn(node_host(test, node), node_port(test, node),
                           timeout=2.0, connect_timeout=2.0)
        conn.close()
        return True


class DocumentCasClient(client.Client):
    """Register per independent key (document_cas.clj:54-110). Reads
    are idempotent → indeterminate reads remap to :fail (with-errors op
    #{:read}); writes/cas stay :info on connection trouble."""

    def __init__(self, conn=None, flag=None, read_mode: str = "majority"):
        self.conn = conn
        self.flag = flag or shared_flag()
        self.read_mode = read_mode

    def open(self, test, node):
        conn = rp.ReqlConn(node_host(test, node), node_port(test, node))
        me = DocumentCasClient(conn, self.flag, self.read_mode)

        def create():
            conn.run(rp.db_create(DB_NAME))
            conn.run(rp.table_create(rp.db(DB_NAME), TBL,
                                     replicas=len(test["nodes"])))

        once(self.flag, create)
        return me

    def _table(self):
        return rp.table(rp.db(DB_NAME), TBL, read_mode=self.read_mode)

    def invoke(self, test, op: Op) -> Op:
        k, v = op.value
        try:
            out = self._invoke(k, v, op)
        except (rp.ReqlError, socket.timeout, TimeoutError,
                ConnectionError, OSError) as e:
            out = op.with_(type="info", error=str(e))
        if op.f == "read" and out.type == "info":
            out = out.with_(type="fail")
        return out

    def _invoke(self, k, v, op: Op) -> Op:
        row = rp.get(self._table(), k)
        if op.f == "read":
            value = self.conn.run(
                rp.default(rp.get_field(row, "val"), None))
            return op.with_(type="ok", value=independent.tuple_(k, value))
        if op.f == "write":
            res = self.conn.run(
                rp.insert(self._table(), {"id": k, "val": v},
                          conflict="update"))
            # an embedded write error (e.g. lost primary) arrives in a
            # SUCCESS_ATOM payload, not a RUNTIME_ERROR response
            if res.get("errors"):
                return op.with_(type="info",
                                error=res.get("first_error", "errors"))
            return op.with_(type="ok")
        if op.f == "cas":
            old, new = v
            res = self.conn.run(rp.update(
                row,
                rp.func(1, rp.branch(
                    rp.eq(rp.get_field(rp.var(1), "val"), old),
                    {"val": new},
                    rp.error("abort"),
                )),
            ))
            if res.get("errors") == 0 and res.get("replaced") == 1:
                return op.with_(type="ok")
            first_error = res.get("first_error", "")
            if res.get("errors") and "abort" not in first_error:
                # an infrastructure error (e.g. lost primary), not our
                # deliberate branch abort — the CAS may have applied
                return op.with_(type="info", error=first_error)
            return op.with_(type="fail")
        raise ValueError(f"unknown op {op.f!r}")

    def close(self, test):
        if self.conn:
            self.conn.close()


class ReconfigureNemesis(nemesis_mod.Nemesis):
    """Randomly reconfigures the table's topology: a random replica
    subset with a random primary, applied via ReQL reconfigure on the
    chosen primary, retried on the transient server-tag/unreachable
    errors a mid-partition cluster throws (rethinkdb.clj:196-231)."""

    RETRIES = 10

    def __init__(self, db_name: str = DB_NAME, table_name: str = TBL):
        self.db_name = db_name
        self.table_name = table_name

    def invoke(self, test, op):
        assert op.f == "reconfigure", op.f
        last: Exception | None = None
        for _ in range(self.RETRIES):
            nodes = list(test["nodes"])
            replicas = random.sample(nodes,
                                     1 + random.randrange(len(nodes)))
            primary = random.choice(replicas)
            try:
                conn = rp.ReqlConn(node_host(test, primary),
                                   node_port(test, primary))
            except OSError as e:
                last = e
                continue
            try:
                res = conn.run(rp.reconfigure(
                    rp.table(rp.db(self.db_name), self.table_name),
                    shards=1,
                    replicas={n: 1 for n in replicas},
                    primary_replica_tag=primary,
                ))
                if res.get("reconfigured") != 1:
                    raise rp.ReqlError(rp.RUNTIME_ERROR, str(res))
                return op.with_(value={"replicas": replicas,
                                       "primary": primary})
            except (rp.ReqlError, OSError) as e:
                # ConnectionError/timeouts are OSError subclasses; the
                # only real filter is which ReqlErrors are transient
                # (rethinkdb.clj:221-231's regex taxonomy)
                msg = str(e)
                last = e
                if (isinstance(e, OSError) or "server tag" in msg
                        or "unreachable" in msg):
                    log.warning("reconfigure caught; retrying: %s", msg)
                    continue
                raise
            finally:
                conn.close()
        return op.with_(value=f"reconfigure-failed: {last}")


def reconfigure_start_stop(t1: float, t2: float) -> gen.Generator:
    """The reference's nemesis feed: partition start/stop cycling with
    a reconfigure interposed between every transition
    (document_cas.clj:176-180's (interpose reconfigure
    (cycle [start stop])))."""

    def cycle():
        while True:
            yield gen.sleep(t1)
            yield {"type": "info", "f": "start"}
            yield {"type": "info", "f": "reconfigure"}
            yield gen.sleep(t2)
            yield {"type": "info", "f": "stop"}
            yield {"type": "info", "f": "reconfigure"}

    return gen.seq(cycle())


def r(test, process):
    return {"type": "invoke", "f": "read", "value": None}


def w(test, process):
    return {"type": "invoke", "f": "write", "value": random.randrange(5)}


def cas(test, process):
    return {"type": "invoke", "f": "cas",
            "value": (random.randrange(5), random.randrange(5))}


def rethinkdb_test(opts: dict) -> dict:
    from ..testlib import noop_test

    db_ = RethinkDB(archive_url=opts.get("archive_url"))
    test = noop_test()
    test.update(opts)
    reconfigure = opts.get("workload") == "reconfigure"
    if reconfigure and opts.get("read_mode") not in (None, "majority"):
        # the reconfigure test is majority/majority BY DESIGN — it
        # "performs only writes and cas ops to prove that data loss
        # isn't just due to stale reads" (document_cas.clj:150-153);
        # silently ignoring the flag would misreport what was tested
        raise ValueError(
            "--workload reconfigure pins --read-mode majority "
            f"(got {opts['read_mode']!r})")
    test.update(
        {
            "name": ("rethinkdb document reconfigure" if reconfigure
                     else "rethinkdb document-cas"),
            "os": osdist.debian,
            "db": db_,
            "client": DocumentCasClient(
                read_mode=("majority" if reconfigure
                           else opts.get("read_mode", "majority"))),
            "nemesis": (
                # topology changes composed with partitions
                # (document_cas.clj:181-185)
                nemesis_mod.compose({
                    frozenset({"reconfigure"}): ReconfigureNemesis(),
                    frozenset({"start", "stop"}):
                        cmn.pick_nemesis(db_, opts),
                }) if reconfigure else cmn.pick_nemesis(db_, opts)),
            "model": models.CASRegister(),
            "checker": checker_mod.compose({
                "perf": checker_mod.perf_checker(),
                "indep": independent.checker(checker_mod.compose({
                    "timeline": checker_mod.timeline_html(),
                    "linear": checker_mod.linearizable(),
                })),
            }),
            "generator": gen.time_limit(
                opts.get("time_limit", 60),
                gen.nemesis(
                    (reconfigure_start_stop(10, 10) if reconfigure
                     else gen.start_stop(10, 10)),
                    independent.concurrent_generator(
                        opts.get("threads_per_key", 2),
                        itertools.count(),
                        # the reconfigure test "performs only writes
                        # and cas ops to prove that data loss isn't
                        # just due to stale reads"
                        # (document_cas.clj:150-153)
                        lambda k: gen.limit(
                            opts.get("ops_per_key", 50),
                            gen.stagger(opts.get("stagger", 0.05),
                                        gen.mix([w, cas] if reconfigure
                                                else [r, w, cas])),
                        ),
                    ),
                ),
            ),
        }
    )
    return test


def _opt_spec(p) -> None:
    cmn.nemesis_opt(p)
    p.add_argument("--archive-url", dest="archive_url", default=None)
    p.add_argument("--read-mode", dest="read_mode", default="majority",
                   choices=["single", "majority", "outdated"])
    p.add_argument("--workload", default="cas",
                   choices=["cas", "reconfigure"])


def main(argv=None) -> None:
    cli.main(
        {**cli.single_test_cmd(rethinkdb_test, opt_spec=_opt_spec),
         **cli.serve_cmd()},
        argv,
    )


if __name__ == "__main__":
    main()
