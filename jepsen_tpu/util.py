"""General-purpose helpers (reference: jepsen/src/jepsen/util.clj).

Thread-parallel maps, retries, timeouts, relative time, interval-set
strings, and latency extraction over histories.
"""

from __future__ import annotations

import concurrent.futures
import contextlib
import math
import threading
import time as _time
from typing import Any, Callable, Iterable, Sequence

NANOS_PER_SECOND = 1_000_000_000
NANOS_PER_MS = 1_000_000


def majority(n: int) -> int:
    """Smallest integer m such that m > n/2 (util.clj:59-62)."""
    return n // 2 + 1


def minority(n: int) -> int:
    """Largest integer m such that m < ceil(n/2) + ... i.e. n - majority(n)."""
    return n - majority(n)


def real_pmap(fn: Callable, coll: Iterable) -> list:
    """Map fn over coll with one real thread per element, propagating the
    first exception (util.clj:46-52). Unlike a pooled map, every element
    gets its own thread immediately — needed when elements block on each
    other (e.g. barriers across nodes)."""
    items = list(coll)
    if not items:
        return []
    results: list[Any] = [None] * len(items)
    errors: list[BaseException] = []
    lock = threading.Lock()

    def run(i, x):
        try:
            results[i] = fn(x)
        except BaseException as e:  # noqa: BLE001 - re-raised below
            with lock:
                errors.append(e)

    threads = [
        threading.Thread(target=run, args=(i, x), daemon=True)
        for i, x in enumerate(items)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise errors[0]
    return results


def bounded_pmap(fn: Callable, coll: Iterable, bound: int | None = None) -> list:
    """Pooled parallel map with at most `bound` workers (util.clj bounded
    concurrency; default = cpu count + 2)."""
    items = list(coll)
    if not items:
        return []
    import os

    bound = bound or (os.cpu_count() or 1) + 2
    with concurrent.futures.ThreadPoolExecutor(max_workers=bound) as ex:
        return list(ex.map(fn, items))


def bounded_pmap_processes(fn: Callable, coll: Iterable,
                           bound: int | None = None) -> list:
    """Like bounded_pmap but over a PROCESS pool, for CPU-bound work the
    GIL would serialize (the pure-Python linearizability searches). fn
    and every item must be picklable. Falls back to the thread pool when
    process workers can't start (e.g. restricted sandboxes)."""
    items = list(coll)
    if not items:
        return []
    import os

    bound = min(bound or (os.cpu_count() or 1), len(items)) or 1
    import pickle
    from concurrent.futures.process import BrokenProcessPool

    try:
        with concurrent.futures.ProcessPoolExecutor(max_workers=bound) as ex:
            return list(ex.map(fn, items))
    except (OSError, PermissionError, pickle.PicklingError, TypeError,
            AttributeError, BrokenProcessPool):
        # can't start workers or can't pickle the payloads (e.g. a
        # checker holding a lock, or spawn-start platforms): degrade to
        # threads instead of voiding the whole analysis
        return bounded_pmap(fn, items, bound=bound)


class RetryError(Exception):
    pass


def with_retry(
    fn: Callable[[], Any],
    retries: int = 3,
    backoff: float = 0.0,
    exceptions: tuple = (Exception,),
) -> Any:
    """Call fn, retrying up to `retries` times on exception
    (util.clj:339-363)."""
    attempt = 0
    while True:
        try:
            return fn()
        except exceptions:
            attempt += 1
            if attempt > retries:
                raise
            if backoff:
                _time.sleep(backoff)


class TimeoutError_(Exception):
    pass


def timeout(seconds: float, fn: Callable[[], Any], default: Any = TimeoutError_):
    """Run fn in a worker thread; on timeout return `default` (or raise if
    default is the TimeoutError_ sentinel). The worker thread is abandoned,
    not interrupted — mirror of util.clj:311-322 where the thread IS
    interrupted; Python offers no safe interrupt, so clients must use their
    own IO timeouts for cleanup."""
    result: list = []
    error: list = []

    def run():
        try:
            result.append(fn())
        except BaseException as e:  # noqa: BLE001
            error.append(e)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(seconds)
    if t.is_alive():
        if default is TimeoutError_:
            raise TimeoutError_(f"timed out after {seconds}s")
        return default
    if error:
        raise error[0]
    return result[0]


_op_log = None


def log_op_logger(op) -> None:
    """Log an op at debug level (util.clj:208-212, called from
    core.clj:383,409)."""
    global _op_log
    if _op_log is None:
        import logging

        _op_log = logging.getLogger("jepsen_tpu.ops")
    _op_log.debug("%s", op)


class CountDownLatch:
    """A latch: count_down() decrements; await_() blocks until zero
    (the JVM CountDownLatch used for worker phase gates, core.clj:174-225)."""

    def __init__(self, count: int):
        self._count = count
        self._cond = threading.Condition()

    def count_down(self) -> None:
        with self._cond:
            if self._count > 0:
                self._count -= 1
                if self._count == 0:
                    self._cond.notify_all()

    def await_(self, timeout: float | None = None) -> bool:
        with self._cond:
            if self._count == 0:
                return True
            return self._cond.wait_for(lambda: self._count == 0, timeout)


# ---------------------------------------------------------------------------
# Relative time (util.clj:271-288)

_relative_origin: int | None = None
_relative_lock = threading.Lock()


def init_relative_time(origin_nanos: int | None = None) -> None:
    """Set the origin for relative-time-nanos (util.clj:271-280)."""
    global _relative_origin
    with _relative_lock:
        _relative_origin = (
            origin_nanos if origin_nanos is not None else _time.monotonic_ns()
        )


def relative_time_nanos() -> int:
    """Nanoseconds since the origin set by init_relative_time
    (util.clj:282-288). Auto-initialises on first use."""
    global _relative_origin
    if _relative_origin is None:
        init_relative_time()
    return _time.monotonic_ns() - _relative_origin


@contextlib.contextmanager
def with_relative_time(elapsed_nanos: int = 0):
    """Scope with a fresh relative-time origin (util.clj:
    with-relative-time). elapsed_nanos backdates the origin — a
    resumed run passes the preempted session's elapsed time so op
    timestamps stay monotone across sessions."""
    prev = _relative_origin
    init_relative_time(_time.monotonic_ns() - int(elapsed_nanos))
    try:
        yield
    finally:
        with _relative_lock:
            globals()["_relative_origin"] = prev


def nanos_to_ms(n: float) -> float:
    return n / NANOS_PER_MS


def ms_to_nanos(m: float) -> float:
    return m * NANOS_PER_MS


def nanos_to_secs(n: float) -> float:
    return n / NANOS_PER_SECOND


def secs_to_nanos(s: float) -> float:
    return s * NANOS_PER_SECOND


# ---------------------------------------------------------------------------
# Pretty things

def integer_interval_set_str(values: Iterable[int]) -> str:
    """Compact string for a set of integers, collapsing runs:
    #{1..3 5 7..9} (util.clj:528-553)."""
    xs = sorted(set(values))
    if not xs:
        return "#{}"
    parts = []
    lo = prev = xs[0]
    for x in xs[1:]:
        if x == prev + 1:
            prev = x
            continue
        parts.append(str(lo) if lo == prev else f"{lo}..{prev}")
        lo = prev = x
    parts.append(str(lo) if lo == prev else f"{lo}..{prev}")
    return "#{" + " ".join(parts) + "}"


def longest_common_prefix(seqs: Sequence[Sequence]) -> list:
    """Longest common prefix of a collection of sequences (util.clj:653-666)."""
    seqs = list(seqs)
    if not seqs:
        return []
    out = []
    for i, x in enumerate(seqs[0]):
        if all(len(s) > i and s[i] == x for s in seqs[1:]):
            out.append(x)
        else:
            break
    return out


def fraction(a: float, b: float) -> float:
    """a/b, but 1 when b is zero (util.clj)."""
    return 1.0 if b == 0 else a / b


# ---------------------------------------------------------------------------
# History-derived series (util.clj:598-651)

def history_latencies(history) -> list:
    """Given a history (sequence of op dicts/Ops), emit the invoke ops with
    :latency (completion time - invoke time, nanos) attached
    (util.clj:598-632). Unmatched invokes get latency None."""
    from .history import op as to_op  # local import to avoid cycle

    out = []
    open_by_process: dict = {}
    for op in map(to_op, history):
        if op.is_invoke:
            rec = {"op": op, "latency": None, "completion": None}
            open_by_process[op.process] = rec
            out.append(rec)
        else:
            rec = open_by_process.pop(op.process, None)
            if rec is not None:
                rec["latency"] = op.time - rec["op"].time
                rec["completion"] = op
    return out


def nemesis_intervals(history, start_fs=("start",), stop_fs=("stop",)) -> list:
    """Pairs of (start-op, stop-op) delimiting nemesis activity windows
    (util.clj:634-651). Histories interleave invocations and completions
    (start start stop stop), so each stop pairs FIFO with the oldest
    unpaired start; unclosed windows get a None stop."""
    from .history import op as to_op  # local import to avoid cycle

    import collections

    pairs = []
    starts: collections.deque = collections.deque()
    for op in map(to_op, history):
        if op.process != "nemesis":
            continue
        if op.f in start_fs:
            starts.append(op)
        elif op.f in stop_fs and starts:
            pairs.append((starts.popleft(), op))
    pairs.extend((s, None) for s in starts)
    return pairs


def random_nonempty_subset(coll, rng=None):
    """A random non-empty subset of coll (util.clj parity; used by the
    clock nemesis generators, nemesis/time.clj:137-165)."""
    import random as _r

    rng = rng or _r
    items = list(coll)
    k = rng.randrange(1, len(items) + 1)
    return rng.sample(items, k)


def rand_exp(mean: float, rng=None) -> float:
    """Exponentially-distributed random delay with the given mean
    (util.clj rand-exp; used by generator.stagger)."""
    import random

    r = rng or random
    return -mean * math.log(1.0 - r.random())
