"""Helpers for mucking around with tests interactively (reference:
jepsen.repl, repl.clj:6-13)."""

from __future__ import annotations

from . import store


def last_test(test_name: str | None = None, store_dir=None) -> dict | None:
    """The most recently run test, optionally filtered by name
    (repl.clj:6-13). Returns the fully loaded test map (history,
    results) or None."""
    if test_name is None:
        return store.latest(store_dir=store_dir)
    runs = store.tests(test_name, store_dir=store_dir)
    if not runs:
        return None
    return store.load(test_name, max(runs), store_dir=store_dir)
