"""Test orchestration: the execution core (reference: jepsen.core,
core.clj).

`run(test)` threads a single immutable-ish test dict through every layer
(core.clj:540-560): connect the control plane, provision the OS, cycle
the DB, spawn one client worker per process plus a nemesis worker, pull
ops from the generator until exhaustion, record the history, then analyze
it with the checker and persist results.

Worker semantics preserved from the reference:
- processes stripe over nodes round-robin (core.clj:485-496)
- a client exception makes the completion :info — the outcome is unknown
  (core.clj:271-304); the process is then reincarnated as process+n so
  every logical process stays single-threaded forever (core.clj:410-427)
- nemesis ops are journaled to every active history (core.clj:338-350)
- workers synchronize setup/run/teardown through latches so no client
  starts before all are ready (core.clj:171-268)
"""

from __future__ import annotations

import datetime
import logging
import queue
import threading
import time as _time
from typing import Any

from . import checker as checker_mod
from . import control, db as db_mod, generator
from .history import Op, index
from .util import (
    CountDownLatch,
    log_op_logger,
    real_pmap,
    relative_time_nanos,
    with_relative_time,
)

log = logging.getLogger("jepsen_tpu.core")


def conj_op(test, op: Op) -> Op:
    """Append an op to the test's history (core.clj:30-38), and to the
    durability WAL when the run carries one (store.HistoryWAL) — so a
    killed run leaves the ops it completed on disk."""
    with test["_history_lock"]:
        test["_history"].append(op)
        # journal INSIDE the critical section: WAL line order must match
        # history order, or the reindexing fallback loader permutes ops
        wal = test.get("_wal")
        if wal is not None:
            wal.append(op)
    return op


class WorkerAbort(Exception):
    pass


class Worker:
    """Synchronized setup/run/teardown lifecycle (core.clj:161-169)."""

    name = "worker"

    def __init__(self):
        self.abort = threading.Event()

    def setup(self):
        pass

    def run(self):
        pass

    def teardown(self):
        pass


def do_worker(worker: Worker, abort_all, run_latch, teardown_latch):
    """Run one worker through its phases with error recovery; returns the
    first error, or None (core.clj:171-225)."""
    error = None
    try:
        log.debug("Starting %s", worker.name)
        worker.setup()
    except BaseException as e:  # noqa: BLE001
        log.warning("Error setting up %s", worker.name, exc_info=True)
        error = e
        abort_all(worker)
    if error is None:
        run_latch.count_down()
        run_latch.await_()
        try:
            worker.run()
        except BaseException as e:  # noqa: BLE001
            if not isinstance(e, WorkerAbort):
                log.warning("Error running %s", worker.name, exc_info=True)
                error = e
            abort_all(worker)
    else:
        run_latch.count_down()
    teardown_latch.count_down()
    teardown_latch.await_()
    try:
        log.debug("Stopping %s", worker.name)
        worker.teardown()
    except BaseException as e:  # noqa: BLE001
        log.warning("Error tearing down %s", worker.name, exc_info=True)
        error = error or e
    return error


def run_workers(test, workers) -> None:
    """Run all workers to completion; re-raise the error of the worker
    that aborted the run, if any (core.clj:227-268)."""
    n = len(workers)
    run_latch = CountDownLatch(n)
    teardown_latch = CountDownLatch(n)
    aborting: list = []
    abort_lock = threading.Lock()

    def abort_all(worker):
        with abort_lock:
            if not aborting:
                aborting.append(worker)
        for w in workers:
            w.abort.set()
        # Wake anyone blocked at a generator barrier; without this a
        # crashed worker leaves phases()/synchronize() waiters deadlocked
        generator.break_barriers()

    results: list = [None] * n
    threads_binding = [generator.NEMESIS] + list(range(test["concurrency"]))

    def runner(i, w):
        with generator.with_threads(threads_binding):
            results[i] = do_worker(w, abort_all, run_latch, teardown_latch)

    threads = [
        threading.Thread(
            target=runner, args=(i, w), name=f"jepsen {w.name}", daemon=True
        )
        for i, w in enumerate(workers)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    with abort_lock:
        if aborting:
            for w, r in zip(workers, results):
                if w is aborting[0] and r is not None:
                    raise r


def invoke_op(op: Op, test, client, abort: threading.Event) -> Op:
    """Apply op to a client; exceptions become :info completions — the
    outcome is unknown (core.clj:271-304)."""
    try:
        completion = client.invoke(test, op)
        completion = completion.with_(time=relative_time_nanos())
    except BaseException as e:  # noqa: BLE001
        if abort.is_set():
            raise
        log.warning("Process %s crashed", op.process, exc_info=True)
        return op.with_(
            type="info",
            time=relative_time_nanos(),
            error=f"indeterminate: {e}",
        )
    t = completion.type
    assert t in ("ok", "fail", "info"), (
        f"client invoke must complete with ok/fail/info, got {completion!r}"
    )
    assert completion.process == op.process
    assert completion.f == op.f
    return completion


class _InvokerThread:
    """A reusable single-purpose thread that runs client invokes so the
    worker can bound its wait. On timeout the worker marks it abandoned
    and walks away; if the hung call ever finishes, the thread notices
    the flag and exits (its late completion is discarded — the process
    was already reincarnated, matching the reference's interrupt
    semantics, generator.clj:409-518)."""

    def __init__(self, name: str):
        self.requests: queue.SimpleQueue = queue.SimpleQueue()
        self.abandoned = False
        self.thread = threading.Thread(
            target=self._loop, daemon=True, name=name
        )
        self.thread.start()

    def _loop(self):
        while True:
            item = self.requests.get()
            if item is None or self.abandoned:
                # don't start work nobody is waiting on (an op whose
                # deadline already expired is abandoned before it runs)
                return
            fn, box, done = item
            try:
                box["result"] = fn()
            except BaseException as e:  # noqa: BLE001
                box["error"] = e
            done.set()
            if self.abandoned:
                return

    def submit(self, fn):
        box: dict = {}
        done = threading.Event()
        self.requests.put((fn, box, done))
        return box, done

    def stop(self):
        self.requests.put(None)


class ClientWorker(Worker):
    """One worker per initial process id, bound to a node
    (core.clj:352-440)."""

    def __init__(self, test, process: int, node):
        super().__init__()
        self.test = test
        self.node = node
        self.process = process
        self.client = None
        self.name = f"worker {process}"
        self._invoker: _InvokerThread | None = None
        self._client_hung = False

    def _open_client(self):
        """open then setup, like the reference's open-compat!
        (client.clj:38-51); the connection is closed if setup fails."""
        client = self.test["client"].open(self.test, self.node)
        try:
            client.setup(self.test)
        except BaseException:
            try:
                client.close(self.test)
            except Exception:  # noqa: BLE001
                log.warning("Error closing client after failed setup",
                            exc_info=True)
            raise
        return client

    def _close_client(self):
        """teardown then close, like the reference's close-compat!
        (client.clj:62-70); close always runs."""
        client, self.client = self.client, None
        if client is None:
            return
        try:
            client.teardown(self.test)
        finally:
            client.close(self.test)

    def setup(self):
        self.client = self._open_client()

    def run(self):
        test = self.test
        gen = test["generator"]
        while True:
            if self.abort.is_set():
                raise WorkerAbort()
            o = generator.op_and_validate(gen, test, self.process)
            if o is None:
                return
            op_deadline = o.pop(generator.DEADLINE_KEY, None)
            op = Op.from_dict(o).with_(
                process=self.process, time=relative_time_nanos()
            )
            if op.type is None:
                op = op.with_(type="invoke")
            log_op_logger(op)
            if self.client is None:
                try:
                    # bare open — no setup: reconnection after a crash must
                    # not re-run one-time DB state setup (core.clj:389)
                    self.client = test["client"].open(test, self.node)
                except Exception as e:  # noqa: BLE001
                    log.warning("Error opening client", exc_info=True)
                    fail = op.with_(
                        type="fail",
                        error=("no-client", str(e)),
                        time=relative_time_nanos(),
                    )
                    conj_op(test, op)
                    conj_op(test, fail)
                    self.client = None
                    continue
            conj_op(test, op)
            completion = self._invoke(op, op_deadline)
            conj_op(test, completion)
            log_op_logger(completion)
            if completion.is_info:
                # All bets are off: the op may or may not have taken
                # effect. The process is hung; reincarnate it so each
                # logical process stays single-threaded (core.clj:410-427).
                self.process += test["concurrency"]
                client, self.client = self.client, None
                if self._client_hung:
                    # the invoker still holds the client mid-call; closing
                    # synchronously could hang this worker too — close
                    # best-effort off-thread (core.clj's interrupt path)
                    self._client_hung = False
                    threading.Thread(
                        target=self._close_quietly,
                        args=(client,),
                        daemon=True,
                        name=f"jepsen close {self.name}",
                    ).start()
                else:
                    self._close_quietly(client)

    def _close_quietly(self, client):
        try:
            # bare close — no teardown: the DB's shared state must
            # survive for the other workers (core.clj:425-427)
            client.close(self.test)
        except Exception:  # noqa: BLE001
            log.warning("Error closing client", exc_info=True)

    def _invoke(self, op: Op, deadline=None) -> Op:
        """Invoke with the wait bounded by op_timeout and the op's
        time-limit deadline (attached by generator.TimeLimit); on expiry
        the op completes :info and the hung invoke is abandoned (the
        reference interrupts the worker thread at the time limit,
        generator.clj:409-518)."""
        test = self.test
        timeout = test.get("op_timeout")
        if deadline is not None:
            remaining = deadline - _time.monotonic()
            timeout = (
                remaining if timeout is None else min(timeout, remaining)
            )
        if timeout is None:
            return invoke_op(op, test, self.client, self.abort)
        if self._invoker is None:
            self._invoker = _InvokerThread(f"jepsen invoker {self.name}")
        invoker = self._invoker
        client = self.client
        box, done = invoker.submit(
            lambda: invoke_op(op, test, client, self.abort)
        )
        if done.wait(max(0.0, timeout)):
            if "error" in box:
                raise box["error"]
            return box["result"]
        invoker.abandoned = True
        # also enqueue the stop sentinel: if the call completed in the
        # instant after wait() expired, the thread may have re-entered
        # get() before seeing abandoned — the sentinel unblocks it so
        # the thread can't leak
        invoker.stop()
        self._invoker = None
        self._client_hung = True
        log.warning(
            "Process %s timed out after %.1fs; abandoning invoke",
            op.process,
            max(0.0, timeout),
        )
        return op.with_(
            type="info",
            time=relative_time_nanos(),
            error="op timed out",
        )

    def teardown(self):
        if self._invoker is not None:
            self._invoker.stop()
            self._invoker = None
        if self._client_hung:
            # teardown/close would block on the hung connection
            client, self.client = self.client, None
            if client is not None:
                threading.Thread(
                    target=self._close_quietly,
                    args=(client,),
                    daemon=True,
                    name=f"jepsen close {self.name}",
                ).start()
            return
        self._close_client()


class NemesisWorker(Worker):
    """Drives the nemesis from the same generator (core.clj:442-473)."""

    name = "nemesis"

    def __init__(self, test):
        super().__init__()
        self.test = test
        self.nemesis = None

    def setup(self):
        self.nemesis = self.test["nemesis"].setup(self.test)
        ledger = self.test.pop("_resume_ledger", None)
        if ledger:
            self._heal_ledger(ledger)

    def _heal_ledger(self, ledger) -> None:
        """Resume contract: every fault the preempted run left planted
        is healed BEFORE the first generated op — this runs in worker
        setup(), and do_worker's run latch releases no worker's run()
        until every setup() finished. Heal ops are journaled like any
        nemesis op, tagged resume_heal so audits can tell them from
        scheduled heals."""
        nem = self.nemesis
        if hasattr(nem, "restore_faults"):
            nem.restore_faults(ledger)
        log.info("Healing %d leftover fault(s) from the preempted run",
                 len(ledger))
        for e in ledger:
            f = e.get("heal_f")
            if not f:
                continue
            op = Op(
                process=generator.NEMESIS, type="info", f=f, value=None,
                time=relative_time_nanos(), extra={"resume_heal": True},
            )
            self._apply(op)

    def run(self):
        test = self.test
        gen = test["generator"]
        while True:
            if self.abort.is_set():
                raise WorkerAbort()
            o = generator.op_and_validate(gen, test, generator.NEMESIS)
            if o is None:
                return
            # nemesis invokes aren't deadline-bounded, but strip the
            # time-limit annotation so it doesn't leak into the history
            o.pop(generator.DEADLINE_KEY, None)
            op = Op.from_dict(o).with_(
                process=generator.NEMESIS, time=relative_time_nanos()
            )
            if op.type is None:
                op = op.with_(type="info")
            self._apply(op)

    @staticmethod
    def _journal(test, wal, op: Op) -> None:
        """Append to every active history; the WAL line lands under the
        MAIN history's lock (nemesis ops bypass conj_op) so WAL order
        matches history order for the reindexing fallback loader."""
        main_lock = test.get("_history_lock")
        journaled = False
        for hist, lock in list(test["active_histories"]):
            with lock:
                hist.append(op)
                if wal is not None and lock is main_lock:
                    wal.append(op)
                    journaled = True
        if wal is not None and not journaled:
            wal.append(op)

    def _apply(self, op: Op) -> Op:
        """Journal to ALL active histories, invoke, journal completion
        (core.clj:338-350); exceptions -> :info (core.clj:308-336)."""
        test = self.test
        log_op_logger(op)
        wal = test.get("_wal")
        self._journal(test, wal, op)
        try:
            completion = self.nemesis.invoke(test, op).with_(
                time=relative_time_nanos()
            )
            assert completion.type == "info", completion
            assert completion.f == op.f, completion
        except BaseException as e:  # noqa: BLE001
            if self.abort.is_set():
                raise
            log.warning("Nemesis crashed", exc_info=True)
            completion = op.with_(
                type="info",
                time=relative_time_nanos(),
                error=f"indeterminate: {e}",
            )
        self._journal(test, wal, completion)
        log_op_logger(completion)
        return completion

    def teardown(self):
        if self.nemesis is not None:
            self.nemesis.teardown(self.test)


#: default seconds between periodic run-state checkpoints
CHECKPOINT_INTERVAL = 5.0


def checkpoint_state(test) -> dict:
    """Assemble the crash-consistent run snapshot store.RunCheckpoint
    persists: generator cursors/rng states, the nemesis active-fault
    ledger, the process table (next process id per worker thread), the
    WAL session epoch, and time anchors. Reads live state without
    locks — a cursor can be at most one draw stale, which resume
    tolerates (the WAL is the ground truth for landed ops)."""
    nem = test.get("nemesis")
    workers = test.get("_client_workers") or []
    wal = test.get("_wal")
    return {
        "v": 1,
        "generator": generator.snapshot(test["generator"]),
        "faults": (list(nem.active_faults())
                   if hasattr(nem, "active_faults") else []),
        "processes": [w.process for w in workers],
        "wal_epoch": getattr(wal, "epoch", 0),
        "wal_count": len(test.get("_history") or ()),
        "elapsed_nanos": relative_time_nanos(),
        "wall_clock": _time.time(),
    }


def checkpoint_now(test):
    """Write a checkpoint immediately; None when the run carries no
    checkpoint store (no name/start_time)."""
    ckpt = test.get("_ckpt")
    if ckpt is None:
        return None
    return ckpt.write(checkpoint_state(test))


def _checkpoint_loop(test, stop: threading.Event) -> None:
    interval = test.get("checkpoint_interval") or CHECKPOINT_INTERVAL
    while not stop.wait(interval):
        try:
            checkpoint_now(test)
        except Exception:  # noqa: BLE001 — checkpointing is best-effort
            log.warning("periodic checkpoint failed", exc_info=True)


def run_case(test) -> list:
    """Spawn nemesis + client workers, run one case, return its history
    (core.clj:475-504). A resumed run pre-seeds the history with the
    prior sessions' WAL ops and restores each worker's process id."""
    history: list = list(test.pop("_prior_history", ()))
    lock = threading.Lock()
    test["_history"] = history
    test["_history_lock"] = lock
    test["active_histories"].append((history, lock))
    wal = None
    ckpt_stop = None
    ticker = None
    if test.get("name") and test.get("start_time"):
        # durability sidecar: every op lands on disk as it happens, so
        # a SIGKILL'd run leaves a partial history load_history can read
        try:
            from . import store

            wal = store.HistoryWAL(test)
            test["_wal"] = wal
        except Exception:  # noqa: BLE001 — best-effort durability
            log.warning("couldn't open history WAL", exc_info=True)
            wal = None
    if wal is not None:
        try:
            from . import store

            test["_ckpt"] = store.RunCheckpoint(test)
            ckpt_stop = threading.Event()
            ticker = threading.Thread(
                target=_checkpoint_loop, args=(test, ckpt_stop),
                daemon=True, name="jepsen checkpoint")
            ticker.start()
        except Exception:  # noqa: BLE001 — checkpointing is best-effort
            log.warning("couldn't open run checkpoint", exc_info=True)
            ckpt_stop = None
    monitor = None
    if test.get("online"):
        # streaming verdicts with bounded lag during the run; on a
        # definite falsification the monitor sets test["_drain"] (the
        # SIGTERM drain gate) so the doomed run winds down early
        try:
            from .online.monitor import RunMonitor

            monitor = RunMonitor(test).start()
        except Exception:  # noqa: BLE001 — monitoring is advisory
            log.warning("couldn't start online monitor", exc_info=True)
            monitor = None
    try:
        nodes = test["nodes"] or [None]
        client_nodes = [
            nodes[i % len(nodes)] for i in range(test["concurrency"])
        ]
        procs = test.pop("_resume_processes", None)
        client_workers = [
            ClientWorker(
                test,
                procs[i] if procs and i < len(procs) else i,
                node,
            )
            for i, node in enumerate(client_nodes)
        ]
        test["_client_workers"] = client_workers
        workers = [NemesisWorker(test)] + client_workers
        run_workers(test, workers)
    finally:
        if monitor is not None:
            monitor.stop()
        if ckpt_stop is not None:
            ckpt_stop.set()
            ticker.join(timeout=2.0)
            try:
                # final checkpoint: post-teardown, so the fault ledger
                # is empty and cursors sit at the drain point
                checkpoint_now(test)
            except Exception:  # noqa: BLE001
                log.warning("final checkpoint failed", exc_info=True)
        test.pop("_ckpt", None)
        test.pop("_client_workers", None)
        test["active_histories"].remove((history, lock))
        if wal is not None:
            test.pop("_wal", None)
            wal.close()
    return history


def snarf_logs(test) -> None:
    """Download DB log files from every node into the store directory
    (core.clj:98-130)."""
    dbo = test.get("db")
    if not isinstance(dbo, db_mod.LogFiles) or not test.get("start_time"):
        return
    try:
        from . import store
    except ImportError:
        return

    def snarf(node):
        for path in dbo.log_files(test, node):
            dest = store.path_(
                test, [str(node), path.lstrip("/").replace("/", "_")]
            )
            try:
                test["remote"].download(node, path, dest)
            except Exception:  # noqa: BLE001
                log.warning("couldn't download %s from %s", path, node)

    real_pmap(snarf, test["nodes"])


class DrainSignal:
    """The PR-5 preemption-drain contract as a reusable primitive:
    the FIRST SIGTERM invokes `on_drain` (which returns True when a
    graceful drain was actually initiated) and the process winds down
    through its normal cleanup; a second SIGTERM — or a first one that
    couldn't start a drain — raises SystemExit(143) so finally blocks
    still fire and containerized runs exit with the conventional
    128+SIGTERM status. Shared by the test-run hook below and the
    resident verdict daemon (jepsen_tpu/serve), whose drain closes the
    admission gate and finishes in-flight verdicts instead of closing
    a generator gate.

    Handlers only install from the main thread (signal module rule);
    elsewhere install() is a no-op and SIGTERM keeps its prior
    disposition."""

    def __init__(self, on_drain, what: str = "run"):
        self.on_drain = on_drain
        self.what = what
        self.draining = threading.Event()
        self._prev = None
        self._installed = False

    def _on_term(self, signum, frame):
        if not self.draining.is_set():
            initiated = False
            try:
                initiated = bool(self.on_drain())
            except Exception:  # noqa: BLE001 — a broken drain hook
                #               must not swallow the terminate request
                log.warning("drain hook failed", exc_info=True)
            if initiated:
                log.warning("SIGTERM: draining %s (send SIGTERM again "
                            "to force exit)", self.what)
                self.draining.set()
                return
        raise SystemExit(143)

    def install(self) -> "DrainSignal":
        import signal

        if threading.current_thread() is threading.main_thread():
            try:
                self._prev = signal.signal(signal.SIGTERM, self._on_term)
                self._installed = True
            except ValueError:
                self._prev = None
        return self

    def uninstall(self) -> None:
        import signal

        if self._installed:
            try:
                signal.signal(signal.SIGTERM, self._prev)
            except ValueError:
                pass
            self._installed = False

    def __enter__(self) -> "DrainSignal":
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False


class _SnarfHook:
    """Crash-time log collection (core.clj:132-149): the reference
    installs a JVM shutdown hook so DB logs still download on ctrl-C.
    Python's finally blocks already run on KeyboardInterrupt, but a
    SIGTERM kills the process without unwinding and a crash *during*
    cleanup can skip the snarf — so while a test runs we (a) turn the
    FIRST SIGTERM into a graceful preemption drain via DrainSignal
    (close the generator gate and let the run wind down, checkpointed
    and resumable; a second SIGTERM forces SystemExit so finally
    blocks still fire), and (b) register an atexit backstop.
    snarf-once semantics keep the normal path from downloading
    twice."""

    def __init__(self, test):
        self.test = test
        self._done = False
        self._lock = threading.Lock()
        self._drain_signal = DrainSignal(self._start_drain, what="run")

    def _start_drain(self) -> bool:
        # graceful preemption drain (TPU maintenance sends SIGTERM):
        # close the generator gate — workers drain in-flight invokes
        # through the normal timeout/:info path, teardown heals active
        # faults, and run_case flushes the WAL and writes a final
        # checkpoint. Without a drain gate there is nothing to drain.
        drain = self.test.get("_drain")
        if drain is None or drain.is_set():
            return False
        self.test["_preempted"] = True
        drain.set()
        return True

    def snarf_once(self) -> None:
        with self._lock:
            if self._done:
                return
            self._done = True
        try:
            snarf_logs(self.test)
        except Exception:  # noqa: BLE001
            log.warning("log snarfing failed", exc_info=True)

    def __enter__(self):
        import atexit

        atexit.register(self.snarf_once)
        self._drain_signal.install()
        return self

    def __exit__(self, *exc):
        import atexit

        atexit.unregister(self.snarf_once)
        self._drain_signal.uninstall()
        return False


def analyze(test) -> dict:
    """Index the history, run the checker, persist results
    (core.clj:506-523). With a store attached, completed analysis units
    journal to analysis.ckpt.jsonl (store.AnalysisJournal) as they
    finish — the independent checker's per-key verdicts and the cycle
    checker's per-component closures — so re-running analysis of a huge
    history skips finished work instead of restarting."""
    log.info("Analyzing...")
    hist = test["history"]
    # run() pre-indexes before save_1; skip the second full re-allocation
    # pass when indexes are already correct (offline analyze of stored
    # histories may still need it).
    if any(o.index != i for i, o in enumerate(hist)):
        test["history"] = index(hist)
    journal = None
    if test.get("name") and test.get("start_time"):
        try:
            from . import store

            journal = store.AnalysisJournal(test)
            test["_analysis_journal"] = journal
        except Exception:  # noqa: BLE001 — journaling is best-effort
            log.warning("couldn't open analysis journal", exc_info=True)
            journal = None
    try:
        test["results"] = checker_mod.check_safe(
            test["checker"], test, test["history"], {}
        )
    finally:
        if journal is not None:
            test.pop("_analysis_journal", None)
            journal.close()
    if test.get("_online_abort") and isinstance(test["results"], dict):
        # early abort changed when the run stopped, not what the batch
        # analysis concluded; surface both
        test["results"]["online-abort"] = test["_online_abort"]
    log.info("Analysis complete")
    if test.get("name") and test.get("start_time"):
        try:
            from . import store

            store.save_2(test)
        except ImportError:
            pass
    return test


def with_recovery_phases(test) -> Any:
    """The recovery contract (nemesis/combined.clj's final-generator):
    once the main generator is exhausted, the nemesis runs every fault
    package's heal generator (test["final_generator"]), then — when
    test["stability_period"] and test["stability_generator"] are set —
    clients run a plain-op stability window so checker.recovery has a
    post-heal view to audit. Phases are barrier-synchronized: no heal
    starts while a client still draws main-phase ops."""
    main = test.get("generator")
    phase_list = [main]
    final = test.get("final_generator")
    if final is not None:
        phase_list.append(generator.nemesis(final))
    period = test.get("stability_period")
    stability = test.get("stability_generator")
    if period and stability is not None:
        phase_list.append(
            generator.time_limit(period, generator.clients(stability)))
    if len(phase_list) == 1:
        return main
    return generator.phases(*phase_list)


def prepare(test: dict) -> dict:
    """Fill in derived test-map fields (core.clj:593-608)."""
    test = dict(test)
    test.setdefault("nodes", [])
    test.setdefault("concurrency", max(1, len(test["nodes"])))
    test.setdefault("start_time", datetime.datetime.now())
    test["active_histories"] = []
    test["remote"] = control.remote_for_test(test)
    # drain gate outermost: a SIGTERM stops generation for EVERY phase,
    # and run/resume snapshot/restore the same generator shape
    test["_drain"] = threading.Event()
    test["generator"] = generator.interruptible(
        with_recovery_phases(test), test["_drain"])
    return test


def run(test: dict) -> dict:
    """Run a complete test: provision, execute, analyze
    (core.clj:539-640). Returns the test dict with :history and :results."""
    test = prepare(test)
    try:
        from . import store

        store.start_logging(test)
    except ImportError:
        store = None  # type: ignore[assignment]

    try:
        # prime per-node connections in parallel, with rollback-free
        # semantics: any failure aborts the run (core.clj:611-620)
        real_pmap(test["remote"].connect, test["nodes"])
        try:
            # OS setup
            osys = test.get("os")
            if osys is not None:
                control.on_nodes(test, osys.setup)
            try:
                # DB cycle (teardown -> setup, with retries)
                if test.get("db") is not None:
                    db_mod.cycle(test)
                with _SnarfHook(test) as hook:
                    try:
                        with with_relative_time():
                            test["history"] = index(run_case(test))
                        preempted = test.pop("_preempted", False)
                        log.info("Run complete, writing")
                        if store is not None and test.get("name"):
                            store.save_1(test)
                        if preempted:
                            # leave the cluster as-is: resuming needs
                            # the DB's on-node state
                            test["_preserve_db"] = True
                            log.warning(
                                "Run preempted; checkpoint + WAL saved "
                                "— continue with `jepsen-tpu resume`")
                            raise SystemExit(143)
                        analyze(test)
                    finally:
                        hook.snarf_once()
                        if (test.get("db") is not None
                                and not test.get("_preserve_db")):
                            control.on_nodes(
                                test,
                                lambda t, n: test["db"].teardown(t, n),
                            )
            finally:
                if osys is not None:
                    control.on_nodes(test, osys.teardown)
        finally:
            for node in test["nodes"]:
                test["remote"].disconnect(node)
        log_results(test)
        return test
    finally:
        if store is not None:
            store.stop_logging(test)


def resume(test: dict) -> dict:
    """Resume a preempted or SIGKILL'd run from its crash-consistent
    checkpoint (the `jepsen-tpu resume` path). The test dict must carry
    the ORIGINAL run's name and start_time (the CLI resolves them from
    the run dir) plus the same seed/options, so prepare() rebuilds a
    structurally identical generator for restore().

    Sequence: salvage the torn-tail-tolerant WAL as the prior history
    (the reopened WAL appends under session epoch last+1, so op indices
    never collide), restore generator/nemesis cursors from the
    checkpoint, heal every fault in the active-fault ledger BEFORE the
    first generated op (NemesisWorker setup), and continue to the
    original time budget. The cluster is NOT re-provisioned — no OS
    setup, no DB cycle — because preserved node state is the point of
    resuming. At-least-once caveat: cursors can trail the WAL by the
    one draw in flight at the kill, so a resumed schedule may re-emit
    that op."""
    from . import store

    assert test.get("name") and test.get("start_time"), (
        "resume needs the original run's name and start_time")
    test = prepare(test)
    ckpt = store.load_checkpoint(test)
    if ckpt is None:
        raise FileNotFoundError(
            f"no usable run checkpoint under {store.path(test)}")
    test["_prior_history"] = store.load_wal_history(test)
    gen_state = ckpt.get("generator")
    if gen_state:
        generator.restore(test["generator"], gen_state)
    ledger = list(ckpt.get("faults") or [])
    if ledger:
        test["_resume_ledger"] = ledger
    procs = ckpt.get("processes")
    if procs:
        test["_resume_processes"] = [int(p) for p in procs]
    log.info(
        "Resuming run %s/%s: %d prior op(s), %d leftover fault(s)",
        test["name"], store.time_str(test["start_time"]),
        len(test["_prior_history"]), len(ledger))
    store.start_logging(test)
    try:
        real_pmap(test["remote"].connect, test["nodes"])
        try:
            with _SnarfHook(test) as hook:
                try:
                    with with_relative_time(
                            int(ckpt.get("elapsed_nanos") or 0)):
                        test["history"] = index(run_case(test))
                    preempted = test.pop("_preempted", False)
                    log.info("Resumed run complete, writing")
                    store.save_1(test)
                    if preempted:
                        test["_preserve_db"] = True
                        log.warning("Resumed run preempted again; "
                                    "state saved for another resume")
                        raise SystemExit(143)
                    analyze(test)
                finally:
                    hook.snarf_once()
                    if (test.get("db") is not None
                            and not test.get("_preserve_db")):
                        control.on_nodes(
                            test, lambda t, n: test["db"].teardown(t, n))
        finally:
            for node in test["nodes"]:
                test["remote"].disconnect(node)
        log_results(test)
        return test
    finally:
        store.stop_logging(test)


def log_results(test) -> dict:
    r = test.get("results", {})
    if r.get("valid") is True:
        log.info("Everything looks good! (valid)")
    elif r.get("valid") == "unknown":
        log.warning("Analysis returned :unknown")
    else:
        log.warning("Analysis invalid!")
    return test
