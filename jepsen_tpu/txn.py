"""Transaction micro-operations.

A transaction value is a sequence of micro-ops, each a 3-element sequence
``[f, k, v]`` where f is "r" or "w". Accessor/predicate parity with
jepsen.txn (reference: txn/src/jepsen/txn/micro_op.clj:1-33).
"""

from __future__ import annotations

READ = "r"
WRITE = "w"
APPEND = "append"  # list-append workloads (Elle's richest inference)


def f(mop):
    """The function this micro-op executes (micro_op.clj:4-7)."""
    return mop[0]


def key(mop):
    """The key this micro-op affects (micro_op.clj:9-12)."""
    return mop[1]


def value(mop):
    """The value this micro-op used (micro_op.clj:14-17)."""
    return mop[2]


def is_read(mop) -> bool:
    return f(mop) == READ


def is_write(mop) -> bool:
    return f(mop) == WRITE


def is_append(mop) -> bool:
    return f(mop) == APPEND


def is_op(mop) -> bool:
    """Is this a legal micro-op (micro_op.clj:29-33)?"""
    try:
        return len(mop) == 3 and f(mop) in (READ, WRITE, APPEND)
    except TypeError:
        return False
