"""Command-line runners (reference: jepsen.cli, cli.clj).

Test authors build a main from subcommand maps, exactly like the
reference's `(cli/run! (merge (cli/single-test-cmd {...}) (cli/serve-cmd))
args)` (cli.clj:229-304, etcd.clj:183-188):

    from jepsen_tpu import cli

    def my_test(opts): ...

    if __name__ == "__main__":
        cli.main(
            {**cli.single_test_cmd(my_test), **cli.serve_cmd()},
            sys.argv[1:],
        )

Exit codes (cli.clj:253-304): 0 success, 1 test ran but results were
invalid, 254 bad arguments / unknown command, 255 internal error.
"""

from __future__ import annotations

import argparse
import logging
import sys
from dataclasses import dataclass, field
from typing import Callable

log = logging.getLogger("jepsen_tpu.cli")

#: The reference's default cluster (cli.clj:17)
DEFAULT_NODES = ["n1", "n2", "n3", "n4", "n5"]


class CliError(Exception):
    """Bad arguments: exits 254."""


class _Parser(argparse.ArgumentParser):
    """argparse, but option errors raise CliError (exit 254) instead of
    argparse's exit(2). conflict_handler="resolve" lets a suite's
    opt_spec redefine a standard option (e.g. --nemesis with its own
    registry names) instead of crashing the parser build."""

    def __init__(self, *args, **kwargs):
        kwargs.setdefault("conflict_handler", "resolve")
        super().__init__(*args, **kwargs)

    def error(self, message):
        raise CliError(message)


def test_opt_spec(parser: argparse.ArgumentParser) -> None:
    """The standard test options (cli.clj:54-92)."""
    parser.add_argument(
        "-n", "--node", action="append", default=None, metavar="HOSTNAME",
        help="Node to run the test on; repeat for multiple nodes.",
    )
    parser.add_argument(
        "--nodes", default=None, metavar="NODE_LIST",
        help="Comma-separated list of node hostnames.",
    )
    parser.add_argument(
        "--nodes-file", default=None, metavar="FILENAME",
        help="File containing node hostnames, one per line.",
    )
    parser.add_argument("--username", default="root", help="Username for logins")
    parser.add_argument("--password", default="root", help="Password for sudo")
    parser.add_argument(
        "--strict-host-key-checking", action="store_true", default=False,
        help="Whether to check host keys",
    )
    parser.add_argument(
        "--ssh-private-key", default=None, metavar="FILE",
        help="Path to an SSH identity file",
    )
    parser.add_argument(
        "--dummy-ssh", action="store_true", default=False,
        help="Don't actually SSH; pretend every command succeeds "
        "(control.clj *dummy* mode)",
    )
    parser.add_argument(
        "--concurrency", default="1n", metavar="NUMBER",
        help="How many workers? An integer, optionally followed by n "
        "to multiply by the node count (e.g. 3n).",
    )
    parser.add_argument(
        "--test-count", type=int, default=1, metavar="NUMBER",
        help="How many times to repeat the test",
    )
    parser.add_argument(
        "--time-limit", type=int, default=60, metavar="SECONDS",
        help="How long the main body of the test runs, in seconds",
    )
    parser.add_argument(
        "--store-dir", default=None, metavar="DIR",
        help="Root directory for test results (default ./store)",
    )
    # The nemesis/seed options default to SUPPRESS, not None: test maps
    # do test.update(opts), and a present-but-None "nemesis" key would
    # clobber a suite's nemesis object.
    parser.add_argument(
        "--nemesis", default=argparse.SUPPRESS, metavar="SPEC",
        help="Fault mode: a suite registry name (e.g. parts), or a "
        "comma-separated list of fault families (partition, clock, "
        "kill, pause, corruption, packet) for a composed nemesis "
        "package with verified recovery. Suites may redefine this "
        "option with their own default.",
    )
    parser.add_argument(
        "--nemesis-interval", type=float, default=argparse.SUPPRESS,
        metavar="SECONDS",
        help="Seconds between scheduled nemesis operations (default 10)",
    )
    parser.add_argument(
        "--nemesis-schedule", default=argparse.SUPPRESS, metavar="FILE",
        help="Replay an exact fault schedule from a JSON schedule "
        "document (nemesis.combined.schedule_to_json, or a "
        "fuzz-discovered schedule's nemesis rendering) instead of "
        "generating one from --nemesis/--seed",
    )
    parser.add_argument(
        "--seed", type=int, default=argparse.SUPPRESS, metavar="N",
        help="Seed the composed nemesis package's RNG so the fault "
        "schedule is reproducible",
    )
    # SUPPRESS for the same reason as --nemesis: absent means "keep
    # the suite's checker", not "clobber it with None"
    parser.add_argument(
        "--checker", default=argparse.SUPPRESS, metavar="NAME",
        help="Replace the suite's checker with a registered one "
        "(jepsen_tpu.checker.REGISTRY): linearizable, cycle, "
        "timeline, clock, perf, recovery, unbridled-optimism",
    )


def parse_concurrency(opts: dict, key: str = "concurrency") -> dict:
    """\"3n\" -> 3 * node count; plain integers parse directly
    (cli.clj:130-145)."""
    c = str(opts.get(key, "1n"))
    unit = 1
    if c.endswith("n"):
        unit = len(opts.get("nodes") or [])
        c = c[:-1]
    try:
        n = int(c)
    except ValueError:
        raise CliError(
            f"--concurrency {opts.get(key)!r} should be an integer "
            "optionally followed by n"
        ) from None
    opts[key] = n * unit
    return opts


def parse_nodes(opts: dict) -> dict:
    """Merge --node/--nodes/--nodes-file into a single :nodes list
    (cli.clj:147-182)."""
    node = opts.pop("node", None)
    nodes = opts.pop("nodes", None)
    nodes_file = opts.pop("nodes_file", None)
    out: list[str] = []
    if nodes_file:
        with open(nodes_file) as f:
            out.extend(line.strip() for line in f if line.strip())
    if nodes:
        out.extend(s.strip() for s in str(nodes).split(",") if s.strip())
    if node:
        out.extend(node)
    opts["nodes"] = out or list(DEFAULT_NODES)
    return opts


def rename_ssh_options(opts: dict) -> dict:
    """Collect ssh-related options under an :ssh map (cli.clj:200-216)."""
    opts["ssh"] = {
        "username": opts.pop("username", "root"),
        "password": opts.pop("password", "root"),
        "strict_host_key_checking": opts.pop("strict_host_key_checking", False),
        "private_key_path": opts.pop("ssh_private_key", None),
        "dummy": opts.pop("dummy_ssh", False),
    }
    return opts


def test_opt_fn(opts: dict) -> dict:
    """The standard transform chain (cli.clj:218-225)."""
    return parse_concurrency(parse_nodes(rename_ssh_options(opts)))


@dataclass
class Subcommand:
    """One CLI subcommand (the reference's subcommand-spec map,
    cli.clj:229-243)."""

    run: Callable[[dict], int | None]
    opt_spec: Callable[[argparse.ArgumentParser], None] | None = None
    opt_fn: Callable[[dict], dict] | None = None
    usage: str | None = None
    extra_opts: list = field(default_factory=list)


def _build_parser(name: str, sub: Subcommand) -> _Parser:
    p = _Parser(prog=f"{sys.argv[0]} {name}", description=sub.usage)
    if sub.opt_spec is not None:
        sub.opt_spec(p)
    for add in sub.extra_opts:
        add(p)
    return p


def run_cli(subcommands: dict, argv: list[str]) -> int:
    """Dispatch a subcommand; returns the process exit code
    (cli.clj:229-304)."""
    if not logging.getLogger().handlers:
        logging.basicConfig(
            level=logging.INFO, format="%(levelname)s [%(name)s] %(message)s"
        )
    command = argv[0] if argv else None
    if command not in subcommands:
        print(f"Usage: {sys.argv[0]} COMMAND [OPTIONS ...]")
        print("Commands:", ", ".join(sorted(subcommands)))
        return 254
    sub = subcommands[command]
    parser = _build_parser(command, sub)
    try:
        try:
            ns = parser.parse_args(argv[1:])
        except CliError as e:
            print(str(e), file=sys.stderr)
            return 254
        opts = vars(ns)
        if sub.opt_fn is not None:
            try:
                opts = sub.opt_fn(opts)
            except CliError as e:
                print(str(e), file=sys.stderr)
                return 254
        try:
            code = sub.run(opts)
        except CliError as e:
            print(str(e), file=sys.stderr)
            return 254
        return int(code) if code else 0
    except SystemExit as e:  # argparse --help, or a run fn calling sys.exit
        if isinstance(e.code, int) or e.code is None:
            return e.code or 0
        print(e.code, file=sys.stderr)
        return 255
    except Exception:  # noqa: BLE001
        log.exception("Oh jeez, I'm sorry, Jepsen broke. Here's why:")
        return 255


def main(subcommands: dict, argv: list[str] | None = None) -> None:
    sys.exit(run_cli(subcommands, sys.argv[1:] if argv is None else argv))


# ---------------------------------------------------------------------------
# Standard subcommands

def _apply_checker(test_map: dict, opts: dict) -> dict:
    """--checker NAME replaces the suite's checker with a registered
    one (checker.resolve); absent leaves the suite's choice alone."""
    name = opts.get("checker")
    if isinstance(name, str):
        from . import checker as checker_mod

        test_map["checker"] = checker_mod.resolve(name)
    return test_map


def _run_test(test_fn, opts) -> int:
    """The `test` subcommand body (cli.clj:355-364): run --test-count
    times; exit 1 if any run's results are invalid."""
    from . import core

    for _ in range(int(opts.get("test_count", 1))):
        test_map = _apply_checker(test_fn(dict(opts)), opts)
        if opts.get("store_dir"):
            test_map.setdefault("store_dir", opts["store_dir"])
        test = core.run(test_map)
        valid = (test.get("results") or {}).get("valid")
        # :unknown does NOT fail the exit code (cli.clj:362: keywords are
        # truthy); only a definite False (or missing) does.
        if valid is False or valid is None:
            return 1
    return 0


def _run_analyze(test_fn, opts) -> int:
    """The `analyze` subcommand (cli.clj:366-397): rebuild the test from
    CLI options (fresh checkers), attach the stored history, re-analyze —
    no cluster needed."""
    from . import core, store

    cli_test = _apply_checker(test_fn(dict(opts)), opts)
    stored = store.latest(store_dir=opts.get("store_dir"))
    if stored is None:
        raise RuntimeError("Not sure what the last test was")
    if stored.get("name") != cli_test.get("name"):
        raise RuntimeError(
            f"Stored test ({stored.get('name')}) and CLI test "
            f"({cli_test.get('name')}) have different names; aborting"
        )
    test = {k: v for k, v in stored.items() if k != "results"}
    test.update(cli_test)
    test["history"] = stored["history"]
    test["start_time"] = stored["start_time"]
    if opts.get("store_dir"):
        test["store_dir"] = opts["store_dir"]
    test = core.analyze(test)
    core.log_results(test)
    valid = (test.get("results") or {}).get("valid")
    # Same exit-code contract as the test subcommand: a definite False or
    # a missing verdict fails; :unknown passes.
    return 1 if valid is False or valid is None else 0


def _resume_opt_spec(parser: argparse.ArgumentParser) -> None:
    parser.add_argument(
        "run_dir", nargs="?", default=None, metavar="RUN_DIR",
        help="A store/<name>/<time> run directory to resume "
        "(default: the latest run in the store).",
    )


def _run_resume(test_fn, opts) -> int:
    """The `resume` subcommand: reload a preempted run's crash-consistent
    checkpoint and torn-tail-tolerant WAL, heal every fault left in the
    active-fault ledger, and continue to the original time budget
    (core.resume). Exit codes match `test`; a second preemption exits
    143 with the state saved for another resume."""
    import os

    from . import core, store

    run_dir = opts.pop("run_dir", None)
    store_dir = opts.get("store_dir")
    if run_dir:
        d = os.path.abspath(run_dir)
        if not os.path.isdir(d):
            raise CliError(f"no such run directory: {run_dir}")
        time_s = os.path.basename(d)
        name = os.path.basename(os.path.dirname(d))
        store_dir = store_dir or os.path.dirname(os.path.dirname(d))
    else:
        found = store._resolve_latest(store_dir)
        if found is None:
            raise RuntimeError("Not sure what the last test was")
        name, time_s = found
    test_map = _apply_checker(test_fn(dict(opts)), opts)
    if test_map.get("name") != name:
        raise RuntimeError(
            f"Stored run ({name}) and CLI test ({test_map.get('name')}) "
            "have different names; aborting")
    test_map["start_time"] = time_s
    if store_dir:
        test_map["store_dir"] = store_dir
    test = core.resume(test_map)
    valid = (test.get("results") or {}).get("valid")
    return 1 if valid is False or valid is None else 0


def single_test_cmd(
    test_fn: Callable[[dict], dict],
    opt_spec: Callable[[argparse.ArgumentParser], None] | None = None,
    opt_fn: Callable[[dict], dict] | None = None,
    usage: str | None = None,
) -> dict:
    """`test` + `analyze` + `resume` subcommands for a test-map constructor
    (cli.clj:323-397). opt_spec adds suite-specific options; opt_fn
    composes after test_opt_fn."""
    fn = (lambda o: opt_fn(test_opt_fn(o))) if opt_fn else test_opt_fn
    extra = [opt_spec] if opt_spec else []
    return {
        "test": Subcommand(
            run=lambda opts: _run_test(test_fn, opts),
            opt_spec=test_opt_spec,
            extra_opts=extra,
            opt_fn=fn,
            usage=usage or "Run a test with standard options.",
        ),
        "analyze": Subcommand(
            run=lambda opts: _run_analyze(test_fn, opts),
            opt_spec=test_opt_spec,
            extra_opts=extra,
            opt_fn=fn,
            usage="Re-analyze the latest stored history with fresh checkers.",
        ),
        "resume": Subcommand(
            run=lambda opts: _run_resume(test_fn, opts),
            opt_spec=test_opt_spec,
            extra_opts=extra + [_resume_opt_spec],
            opt_fn=fn,
            usage="Resume a preempted run from its checkpoint: heal "
            "leftover faults, reload the WAL, continue to the original "
            "time budget.",
        ),
    }


def serve_cmd() -> dict:
    """The `serve` subcommand: web UI over the store (cli.clj:306-321),
    or — with ``--daemon`` — the resident verdict service (serve/):
    AOT-warmed engines behind the durable check queue."""

    def opt_spec(p):
        p.add_argument("-b", "--host", default="0.0.0.0", help="Bind host")
        p.add_argument("-p", "--port", type=int, default=8080, help="Bind port")
        p.add_argument(
            "--store-dir", default=None, metavar="DIR",
            help="Root directory for test results (default ./store)",
        )
        p.add_argument(
            "--daemon", action="store_true",
            help="Run the resident verdict daemon (submit/verdict/stream "
            "API) instead of the web UI",
        )
        p.add_argument(
            "--queue-dir", default=None, metavar="DIR",
            help="[daemon] Durable queue directory "
            "(default <store-dir>/serve-queue)",
        )
        p.add_argument(
            "--bundle-dir", default=None, metavar="DIR",
            help="[daemon] AOT engine bundle directory; 'off' disables "
            "(default ~/.cache/jepsen-tpu/bundle)",
        )
        p.add_argument(
            "--max-pending", type=int, default=None, metavar="N",
            help="[daemon] Admission bound: reject submissions past N "
            "pending jobs (HTTP 429 + Retry-After)",
        )
        p.add_argument(
            "--max-attempts", type=int, default=None, metavar="N",
            help="[daemon] Dead-letter bound: quarantine a job whose "
            "check has crashed the worker N times, committing an "
            "'unknown: quarantined' verdict (default 3)",
        )

    def run(opts):
        from . import web

        if opts.get("daemon"):
            from .serve.daemon import run_daemon

            return run_daemon(opts)
        # Preimport before the socket goes up: serve_until_signal's
        # first `from .core import DrainSignal` drags in jax, and a
        # SIGTERM arriving during those seconds would hit the default
        # disposition instead of the drain handler.
        from .core import DrainSignal  # noqa: F401

        server = web.serve(
            host=opts["host"], port=opts["port"], store_dir=opts.get("store_dir")
        )
        log.info("Listening on http://%s:%s/", opts["host"], server.server_port)
        # SIGTERM drains and exits 143 so process managers see a clean
        # signal-shaped stop; ctrl-C still exits 0
        return web.serve_until_signal(server)

    return {"serve": Subcommand(run=run, opt_spec=opt_spec)}


def _load_mesh_doctor():
    """Load tools/mesh_doctor.py (a script dir, not a package) by path,
    relative to the repo checkout this package lives in."""
    import importlib.util
    import os

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "mesh_doctor.py")
    if not os.path.exists(path):
        raise CliError(f"mesh doctor tool not found at {path}")
    spec = importlib.util.spec_from_file_location("mesh_doctor", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def doctor_cmd() -> dict:
    """The `doctor` subcommand: examine the device mesh — topology,
    per-device verdict parity against the host oracle, mesh-sharded
    WGL/closure parity and walls, HBM headroom (tools/mesh_doctor)."""

    def opt_spec(p):
        p.add_argument(
            "--mesh", type=int, default=None, metavar="N",
            help="Force an N-device virtual CPU mesh (must be a fresh "
            "process: device count is fixed once jax initializes)",
        )
        p.add_argument(
            "--closure-n", type=int, default=100, metavar="N",
            help="Side of the biggest closure parity matrix",
        )

    def run(opts):
        import json

        doctor = _load_mesh_doctor()
        report = doctor.diagnose(n_devices=opts.get("mesh"),
                                 closure_n=opts.get("closure_n", 100))
        print(json.dumps(report, indent=2, sort_keys=True))
        return 0 if report["ok"] else 1

    return {"doctor": Subcommand(
        run=run, opt_spec=opt_spec,
        usage="Examine the device mesh: topology, per-device parity, "
        "mesh-path parity, HBM headroom.")}


def fuzz_cmd() -> dict:
    """The `fuzz` subcommand: coverage-guided fault-schedule fuzzing
    over batched on-device cluster simulations (fuzz/). Each round
    simulates --clusters seeded clusters in ONE supervised device
    launch, scores every trace through the cycle checker, and keeps
    schedules that hit new coverage buckets; discovered anomalies land
    in <corpus-dir>/anomalies.jsonl for replay parity."""

    def opt_spec(p):
        p.add_argument(
            "--corpus-dir", default="store/fuzz", metavar="DIR",
            help="Corpus directory (checkpointed each round; resumes)",
        )
        p.add_argument(
            "--rounds", type=int, default=4, metavar="N",
            help="Total rounds the corpus should reach (a resumed "
            "corpus runs only the remainder)",
        )
        p.add_argument(
            "--clusters", type=int, default=256, metavar="N",
            help="Simulated clusters per round (one device launch)",
        )
        p.add_argument(
            "--seed", type=int, default=0, metavar="N",
            help="Fuzz seed: the whole run is a pure function of it",
        )
        p.add_argument(
            "--families", default=None, metavar="LIST",
            help="Comma-separated fault families to draw schedules "
            "from (default: all six)",
        )
        p.add_argument(
            "--engine", default=None, metavar="NAME",
            help="Pin the simulator engine (host, tpu); default rides "
            "the supervised sim ladder with host fallback",
        )
        p.add_argument(
            "--fuzz-nodes", type=int, default=None, metavar="N",
            help="Simulated nodes per cluster (default 5)",
        )
        p.add_argument(
            "--keys", type=int, default=None, metavar="N",
            help="Keys per simulated workload (default 8)",
        )
        p.add_argument(
            "--txns", type=int, default=None, metavar="N",
            help="Transactions per simulated cluster (default 24)",
        )
        p.add_argument(
            "--fault-slots", type=int, default=None, metavar="N",
            help="Fault slots per schedule (default 8)",
        )
        p.add_argument(
            "--deadline-ms", type=int, default=None, metavar="MS",
            help="Wall-clock budget per round's scoring launch: "
            "traces whose closures don't fit score unknown (never "
            "kept) instead of wedging the campaign",
        )

    def run(opts):
        import json

        from .fuzz.loop import run_fuzz

        summary = run_fuzz({
            "corpus_dir": opts["corpus_dir"],
            "rounds": opts.get("rounds"),
            "clusters": opts.get("clusters"),
            "seed": opts.get("seed"),
            "families": opts.get("families"),
            "engine": opts.get("engine"),
            "nodes_n": opts.get("fuzz_nodes"),
            "keys": opts.get("keys"),
            "txns": opts.get("txns"),
            "fault_slots": opts.get("fault_slots"),
            "deadline_ms": opts.get("deadline_ms"),
        })
        print(json.dumps(summary, indent=2, sort_keys=True))
        return 0

    return {"fuzz": Subcommand(
        run=run, opt_spec=opt_spec,
        usage="Coverage-guided fault-schedule fuzzing over batched "
        "simulated clusters; anomalies accumulate in the corpus for "
        "replay parity.")}


def watch_cmd() -> dict:
    """The `watch` subcommand: stream a history WAL or foreign trace
    (Jepsen EDN, OTLP-ish span JSONL) through the online frontiers,
    printing one JSON verdict line per window. Verdicts are
    bit-identical to the batch checker on every checked prefix; with a
    state dir they are crash-safe — a SIGKILL'd watch resumed over the
    same stream re-emits nothing and misses nothing."""

    def opt_spec(p):
        p.add_argument(
            "trace", metavar="PATH",
            help="history WAL (history.wal.jsonl), Jepsen EDN history, "
            "or span-log JSONL")
        p.add_argument(
            "--follow", action="store_true",
            help="Tail the WAL for appended ops instead of reading it "
            "once (native WALs only)")
        p.add_argument(
            "--window", type=int, default=256, metavar="N",
            help="Ops per verdict window (the lag bound)")
        p.add_argument(
            "--workload", default="cycle", metavar="NAME",
            help="Serve-registry workload that rehydrates + checks the "
            "ops (cycle, register)")
        p.add_argument(
            "--state-dir", default=None, metavar="DIR",
            help="Durable session state: the fsync'd verdict log and "
            "the closure/per-key memo journal (enables SIGKILL-safe "
            "resume)")
        p.add_argument(
            "--abort-on-invalid", action="store_true",
            help="Stop consuming at the first definite falsification "
            "(invalidity is monotone under extension)")
        p.add_argument(
            "--max-ops", type=int, default=None, metavar="N",
            help="Stop after N ops (deterministic end for a tailed "
            "stream)")
        p.add_argument(
            "--poll", type=float, default=0.05, metavar="SECONDS",
            help="Tail poll interval")
        p.add_argument(
            "--deadline-ms", type=int, default=None, metavar="MS",
            help="Wall-clock budget per verdict window: keys that "
            "don't fit get 'unknown: deadline' this window and are "
            "retried on the next, so one slow window never stalls the "
            "stream")

    def run(opts):
        from .online.watch import run_watch

        try:
            return run_watch(opts)
        except ValueError as e:
            raise CliError(str(e)) from e

    return {"watch": Subcommand(
        run=run, opt_spec=opt_spec,
        usage="Stream a WAL or foreign trace through the online "
        "checker frontiers; one JSON verdict line per window, exit 1 "
        "on a definite falsification.")}


if __name__ == "__main__":  # the reference's jepsen.cli/-main (cli.clj:399-402)
    main({**serve_cmd(), **doctor_cmd(), **fuzz_cmd(), **watch_cmd()})
