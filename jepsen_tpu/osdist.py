"""Concrete OS provisioning for Debian and CentOS nodes (reference:
jepsen.os.debian os/debian.clj:1-169 and jepsen.os.centos
os/centos.clj:1-160).

Functions take an explicit (remote, node) pair. The OS objects install
the same base tool set the reference does (wget, iptables, psmisc,
ntpdate, faketime, ...) and heal the network on setup."""

from __future__ import annotations

import logging

from .control import Remote
from .control.util import exists
from .osenv import OS

log = logging.getLogger("jepsen_tpu.osdist")

#: base packages every node gets (os/debian.clj:147-165)
BASE_PACKAGES = [
    "wget", "curl", "unzip", "iptables", "psmisc", "tar", "bzip2",
    "ntpdate", "faketime", "iputils-ping", "iproute2", "rsyslog",
    "logrotate",
]


# ---------------------------------------------------------------------------
# Debian

def setup_hostfile(remote: Remote, node) -> None:
    """Ensure /etc/hosts maps loopback to plain localhost
    (os/debian.clj:12-25)."""
    hosts = remote.exec(node, ["cat", "/etc/hosts"]).out
    lines = [
        "127.0.0.1\tlocalhost" if line.startswith("127.0.0.1\t") else line
        for line in hosts.splitlines()
    ]
    new = "\n".join(lines)
    if new != hosts:
        remote.exec(node, ["tee", "/etc/hosts"], stdin=new, sudo=True)


def time_since_last_update(remote: Remote, node) -> int:
    """Seconds since the last apt-get update (os/debian.clj:27-31)."""
    try:
        now = int(remote.exec(node, ["date", "+%s"]).out)
    except ValueError:
        return 0  # dummy-mode remote: treat the cache as fresh
    r = remote.exec(
        node,
        "stat -c %Y /var/cache/apt/pkgcache.bin || echo 0",
        check=False,
    )
    try:
        last = int(r.out.split()[-1])
    except (ValueError, IndexError):
        last = 0
    return now - last


def update(remote: Remote, node) -> None:
    """apt-get update (os/debian.clj:33-36)."""
    remote.exec(node, ["apt-get", "update"], sudo=True)


def maybe_update(remote: Remote, node) -> None:
    """apt-get update at most once a day (os/debian.clj:38-42)."""
    if time_since_last_update(remote, node) > 86400:
        update(remote, node)


def installed(remote: Remote, node, pkgs) -> set:
    """Subset of pkgs currently installed (os/debian.clj:44-54)."""
    pkgs = [str(p) for p in pkgs]
    r = remote.exec(node, ["dpkg", "--get-selections", *pkgs], check=False)
    out = set()
    for line in r.out.splitlines():
        parts = line.split()
        if len(parts) >= 2 and parts[1] == "install":
            out.add(parts[0])
    return out


def is_installed(remote: Remote, node, pkgs) -> bool:
    """Are all of the given packages installed (os/debian.clj:63-68)?"""
    pkgs = [str(p) for p in pkgs]
    return set(pkgs) <= installed(remote, node, pkgs)


def installed_version(remote: Remote, node, pkg) -> str | None:
    """Version of an installed package, or None (os/debian.clj:70-76)."""
    import re

    out = remote.exec(node, ["apt-cache", "policy", str(pkg)], check=False).out
    m = re.search(r"Installed: (\S+)", out)
    if m and m.group(1) != "(none)":
        return m.group(1)
    return None


def uninstall(remote: Remote, node, pkgs) -> None:
    """Purge packages (os/debian.clj:56-61)."""
    pkgs = [pkgs] if isinstance(pkgs, str) else list(pkgs)
    present = installed(remote, node, pkgs)
    if present:
        remote.exec(
            node,
            ["apt-get", "remove", "--purge", "-y", *sorted(present)],
            sudo=True,
        )


def install(remote: Remote, node, pkgs) -> None:
    """Ensure packages are installed; a dict pins versions
    (os/debian.clj:78-99)."""
    if isinstance(pkgs, dict):
        for pkg, version in pkgs.items():
            if installed_version(remote, node, pkg) != version:
                log.info("Installing %s %s", pkg, version)
                remote.exec(
                    node,
                    ["env", "DEBIAN_FRONTEND=noninteractive", "apt-get",
                     "install", "-y", f"{pkg}={version}"],
                    sudo=True,
                )
        return
    pkgs = {str(p) for p in pkgs}
    missing = pkgs - installed(remote, node, pkgs)
    if missing:
        log.info("Installing %s", sorted(missing))
        remote.exec(
            node,
            ["env", "DEBIAN_FRONTEND=noninteractive", "apt-get", "install",
             "-y", *sorted(missing)],
            sudo=True,
        )


def add_key(remote: Remote, node, keyserver: str, key: str) -> None:
    """Receive an apt key (os/debian.clj:101-107)."""
    remote.exec(
        node,
        ["apt-key", "adv", "--keyserver", keyserver, "--recv", key],
        sudo=True,
    )


def add_repo(remote: Remote, node, repo_name: str, apt_line: str,
             keyserver: str | None = None, key: str | None = None) -> None:
    """Add an apt repo + optional key, then update
    (os/debian.clj:109-120)."""
    list_file = f"/etc/apt/sources.list.d/{repo_name}.list"
    if not exists(remote, node, list_file):
        log.info("setting up %s apt repo", repo_name)
        if keyserver or key:
            add_key(remote, node, keyserver, key)
        remote.exec(node, ["tee", list_file], stdin=apt_line, sudo=True)
        update(remote, node)


def install_jdk(remote: Remote, node) -> None:
    """Ensure a JDK is present (os/debian.clj:122-136 installs Oracle
    jdk8 via the long-dead webupd8 PPA; modern Debian ships OpenJDK in
    main, so we install that instead of resurrecting the PPA dance)."""
    install(remote, node, ["default-jdk-headless"])


class Debian(OS):
    """Debian provisioning: hostfile, apt update, base packages, heal
    the network (os/debian.clj:138-169)."""

    def setup(self, test, node) -> None:
        log.info("%s setting up debian", node)
        remote = test["remote"]
        setup_hostfile(remote, node)
        maybe_update(remote, node)
        install(remote, node, BASE_PACKAGES)
        try:
            net = test.get("net")
            if net is not None:
                net.heal(test)
        except Exception:  # noqa: BLE001
            log.warning("net heal failed during OS setup", exc_info=True)

    def teardown(self, test, node) -> None:
        pass


debian = Debian()


# ---------------------------------------------------------------------------
# CentOS

def centos_setup_hostfile(remote: Remote, node) -> None:
    """Append the hostname to the loopback line (os/centos.clj:12-25)."""
    name = remote.exec(node, ["hostname"]).out.strip()
    hosts = remote.exec(node, ["cat", "/etc/hosts"]).out
    lines = [
        f"{line} {name}"
        if line.startswith("127.0.0.1") and name not in line
        else line
        for line in hosts.splitlines()
    ]
    remote.exec(node, ["tee", "/etc/hosts"], stdin="\n".join(lines), sudo=True)


def centos_installed(remote: Remote, node, pkgs) -> set:
    """Subset of pkgs yum reports installed (os/centos.clj:50-61)."""
    import re

    pkgs = {str(p) for p in pkgs}
    out = remote.exec(node, ["yum", "list", "installed"], check=False).out
    found = set()
    for line in out.splitlines():
        first = line.split()[0] if line.split() else ""
        m = re.match(r"(.*)\.[^\-.]+$", first)
        if m:
            found.add(m.group(1))
    return pkgs & found


def centos_install(remote: Remote, node, pkgs) -> None:
    """Ensure packages are installed via yum (os/centos.clj:92-112)."""
    pkgs = {str(p) for p in pkgs}
    missing = pkgs - centos_installed(remote, node, pkgs)
    if missing:
        log.info("Installing %s", sorted(missing))
        remote.exec(node, ["yum", "-y", "install", *sorted(missing)],
                    sudo=True)


class CentOS(OS):
    """CentOS provisioning via yum (os/centos.clj:133-160)."""

    PACKAGES = [
        "wget", "curl", "unzip", "iptables", "psmisc", "tar", "bzip2",
        "ntpdate", "iputils", "iproute", "rsyslog", "logrotate",
    ]

    def setup(self, test, node) -> None:
        log.info("%s setting up centos", node)
        remote = test["remote"]
        centos_setup_hostfile(remote, node)
        centos_install(remote, node, self.PACKAGES)
        try:
            net = test.get("net")
            if net is not None:
                net.heal(test)
        except Exception:  # noqa: BLE001
            log.warning("net heal failed during OS setup", exc_info=True)

    def teardown(self, test, node) -> None:
        pass


centos = CentOS()


# ---------------------------------------------------------------------------
# SmartOS (os/smartos.clj:1-132): pkgin package management, loopback
# hostfile entry, ipfilter service.


def smartos_setup_hostfile(remote: Remote, node) -> None:
    """Ensure /etc/hosts' loopback line mentions the local hostname
    (os/smartos.clj:12-25) — same append-hostname behavior as CentOS,
    so reuse it (the centos variant matches both tab- and
    space-separated loopback lines)."""
    centos_setup_hostfile(remote, node)


def smartos_time_since_last_update(remote: Remote, node) -> int:
    """Seconds since the last pkgin update (os/smartos.clj:27-31)."""
    now = int(remote.exec(node, ["date", "+%s"]).out.strip())
    then = int(remote.exec(
        node, ["stat", "-c", "%Y", "/var/db/pkgin/sql.log"]).out.strip())
    return now - then


def smartos_update(remote: Remote, node) -> None:
    remote.exec(node, ["pkgin", "update"], sudo=True)


def smartos_maybe_update(remote: Remote, node) -> None:
    """pkgin update if we haven't in a day (os/smartos.clj:37-43)."""
    try:
        if smartos_time_since_last_update(remote, node) > 86400:
            smartos_update(remote, node)
    except Exception:  # noqa: BLE001 — missing sql.log etc.
        smartos_update(remote, node)


def _pkgin_list(remote: Remote, node) -> dict:
    """{package-name: version} from `pkgin -p list` lines like
    "name-1.2.3;..." (os/smartos.clj:45-57,72-84)."""
    out = {}
    listing = remote.exec(node, ["pkgin", "-p", "list"]).out
    for line in listing.splitlines():
        full = line.split(";", 1)[0].strip()
        if not full or "-" not in full:
            continue
        name_part, _, version = full.rpartition("-")
        if name_part:
            out[name_part] = version
    return out


def smartos_installed(remote: Remote, node, pkgs) -> set:
    pkgs = {str(p) for p in pkgs}
    return pkgs & set(_pkgin_list(remote, node))


def smartos_installed_version(remote: Remote, node, pkg) -> str | None:
    return _pkgin_list(remote, node).get(str(pkg))


def smartos_uninstall(remote: Remote, node, pkgs) -> None:
    present = smartos_installed(remote, node, pkgs)
    if present:
        remote.exec(node, ["pkgin", "-y", "remove", *sorted(present)],
                    sudo=True)


def smartos_install(remote: Remote, node, pkgs) -> None:
    """Ensure packages are installed; a dict pins versions
    (os/smartos.clj:86-105)."""
    if isinstance(pkgs, dict):
        versions = _pkgin_list(remote, node)  # one listing for all pins
        for pkg, version in pkgs.items():
            if versions.get(str(pkg)) != version:
                log.info("Installing %s %s", pkg, version)
                remote.exec(
                    node, ["pkgin", "-y", "install", f"{pkg}-{version}"],
                    sudo=True,
                )
        return
    pkgs = {str(p) for p in pkgs}
    missing = pkgs - smartos_installed(remote, node, pkgs)
    if missing:
        log.info("Installing %s", sorted(missing))
        remote.exec(node, ["pkgin", "-y", "install", *sorted(missing)],
                    sudo=True)


class SmartOS(OS):
    """SmartOS provisioning via pkgin; enables the ipfilter service the
    ipfilter Net impl depends on (os/smartos.clj:107-132)."""

    PACKAGES = ["wget", "curl", "vim", "unzip", "rsyslog", "logrotate"]

    def setup(self, test, node) -> None:
        log.info("%s setting up smartos", node)
        remote = test["remote"]
        smartos_setup_hostfile(remote, node)
        smartos_maybe_update(remote, node)
        smartos_install(remote, node, self.PACKAGES)
        remote.exec(node, ["svcadm", "enable", "-r", "ipfilter"], sudo=True)
        try:
            net = test.get("net")
            if net is not None:
                net.heal(test)
        except Exception:  # noqa: BLE001
            log.warning("net heal failed during OS setup", exc_info=True)

    def teardown(self, test, node) -> None:
        pass


smartos = SmartOS()
