"""Web interface for browsing the store (reference: jepsen.web, web.clj).

Routes (web.clj:328-334):
    /                       test table: name / time / validity, color-coded
                            (web.clj:122-134), newest first
    /files/<path>           directory browser + file view under the store
                            root, with path traversal confined to the
                            store (web.clj:279-326)
    /files/<run-dir>.zip    zip download of one run dir (web.clj:256-277)

Implementation is the standard library's threading HTTP server — no
framework dependency (the reference uses http-kit + ring + hiccup).
"""

from __future__ import annotations

import html
import io
import json
import logging
import os
import threading
import zipfile
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import quote, unquote

from . import store

log = logging.getLogger("jepsen_tpu.web")

_CSS = """
body { font-family: sans-serif; margin: 2em; }
table { border-collapse: collapse; }
td, th { padding: 0.3em 1em; border-bottom: 1px solid #ddd; text-align: left; }
.valid-true { background: #cfc; }
.valid-false { background: #fcc; }
.valid-unknown { background: #ffc; }
a { text-decoration: none; }
"""


def _run_validity(run_dir: str):
    """Peek at a run's results.json for its validity (web.clj:48-69 reads
    the stored test; we only need valid)."""
    p = os.path.join(run_dir, "results.json")
    try:
        with open(p) as f:
            return json.load(f).get("valid")
    except (OSError, ValueError):
        return None


def _test_rows(root: str) -> list[dict]:
    rows = []
    for name, runs in store.tests(store_dir=root).items():
        for t, d in runs.items():
            rows.append(
                {
                    "name": name,
                    "time": t,
                    "dir": d,
                    "valid": _run_validity(d),
                }
            )
    rows.sort(key=lambda r: r["time"], reverse=True)
    return rows


def _page(title: str, body: str) -> bytes:
    return (
        f"<!doctype html><html><head><title>{html.escape(title)}</title>"
        f"<style>{_CSS}</style></head><body>{body}</body></html>"
    ).encode()


def _home_html(root: str) -> bytes:
    rows = []
    for r in _test_rows(root):
        v = r["valid"]
        cls = {True: "valid-true", False: "valid-false"}.get(v, "valid-unknown")
        vtxt = {True: "valid", False: "invalid", None: "?"}.get(v, str(v))
        rel = f"{r['name']}/{r['time']}"
        rows.append(
            f'<tr class="{cls}">'
            f'<td><a href="/files/{quote(rel)}/">{html.escape(r["name"])}</a></td>'
            f'<td><a href="/files/{quote(rel)}/">{html.escape(r["time"])}</a></td>'
            f"<td>{html.escape(vtxt)}</td>"
            f'<td><a href="/files/{quote(rel)}.zip">zip</a></td></tr>'
        )
    body = (
        "<h1>Jepsen-TPU</h1><table><tr><th>Test</th><th>Time</th>"
        "<th>Valid?</th><th></th></tr>" + "".join(rows) + "</table>"
    )
    return _page("Jepsen-TPU", body)


def _dir_html(root: str, rel: str, full: str) -> bytes:
    entries = sorted(os.listdir(full))
    items = ['<li><a href="../">..</a></li>']
    for e in entries:
        suffix = "/" if os.path.isdir(os.path.join(full, e)) else ""
        items.append(
            f'<li><a href="{quote(e)}{suffix}">{html.escape(e)}{suffix}</a></li>'
        )
    body = f"<h1>/{html.escape(rel)}</h1><ul>{''.join(items)}</ul>"
    return _page(rel or "store", body)


def _zip_bytes(full: str) -> bytes:
    """Zip an entire run directory in memory (web.clj:256-277 streams;
    run dirs are small — text, json, plots)."""
    buf = io.BytesIO()
    base = os.path.basename(full.rstrip("/"))
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
        for dirpath, _dirs, files in os.walk(full):
            for f in files:
                p = os.path.join(dirpath, f)
                z.write(p, os.path.join(base, os.path.relpath(p, full)))
    return buf.getvalue()


_CONTENT_TYPES = {
    ".txt": "text/plain; charset=utf-8",
    ".log": "text/plain; charset=utf-8",
    ".json": "application/json",
    ".jsonl": "text/plain; charset=utf-8",
    ".html": "text/html; charset=utf-8",
    ".svg": "image/svg+xml",
    ".png": "image/png",
    ".zip": "application/zip",
}


class _Handler(BaseHTTPRequestHandler):
    store_root = store.BASE_DIR

    def log_message(self, fmt, *args):  # route to logging, not stderr
        log.debug("%s %s", self.address_string(), fmt % args)

    def _send(self, code: int, body: bytes, ctype="text/html; charset=utf-8"):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802
        try:
            self._route()
        except BrokenPipeError:
            pass
        except Exception:  # noqa: BLE001
            log.exception("error serving %s", self.path)
            self._send(500, b"internal error", "text/plain")

    def _route(self):
        root = os.path.abspath(self.store_root)
        path = unquote(self.path.split("?", 1)[0])
        if path in ("", "/"):
            return self._send(200, _home_html(root))
        if not path.startswith("/files/"):
            return self._send(404, b"not found", "text/plain")
        rel = path[len("/files/"):]
        want_zip = rel.endswith(".zip")
        if want_zip:
            rel = rel[:-4]
        # Confine to the store root (web.clj:279-310's scope check).
        # realpath, not abspath: a symlink inside the store pointing out
        # of it must not escape. The store's own latest/current links
        # also resolve within the root, so they still browse fine.
        root = os.path.realpath(root)
        full = os.path.realpath(os.path.join(root, rel))
        if not (full == root or full.startswith(root + os.sep)):
            return self._send(403, b"forbidden", "text/plain")
        if not os.path.exists(full):
            return self._send(404, b"not found", "text/plain")
        if want_zip:
            # Only single run dirs zip (store/<name>/<time>); zipping the
            # whole store into memory is an easy OOM.
            depth = len(os.path.relpath(full, root).split(os.sep))
            if not os.path.isdir(full) or depth != 2:
                return self._send(404, b"only run directories zip", "text/plain")
            return self._send(200, _zip_bytes(full), "application/zip")
        if os.path.isdir(full):
            return self._send(200, _dir_html(root, rel.rstrip("/"), full))
        ext = os.path.splitext(full)[1].lower()
        ctype = _CONTENT_TYPES.get(ext, "application/octet-stream")
        with open(full, "rb") as f:
            return self._send(200, f.read(), ctype)


def serve(host="0.0.0.0", port=8080, store_dir=None) -> ThreadingHTTPServer:
    """Start the server in a daemon thread; returns the server (bound
    port at .server_port) — web.clj:336-341."""
    handler = type(
        "Handler",
        (_Handler,),
        {"store_root": store_dir or store.BASE_DIR},
    )
    server = ThreadingHTTPServer((host, port), handler)
    t = threading.Thread(target=server.serve_forever, daemon=True, name="web")
    t.start()
    return server


def serve_until_signal(server, on_drain=None, what="web UI",
                       poll_s: float = 1.0) -> int:
    """Block until ctrl-C or SIGTERM, then shut `server` down cleanly.

    Returns the exit status the CLI should use: 0 for a ctrl-C, 143
    (128+SIGTERM) for a terminate — the conventional status container
    runtimes and TPU preemption agents expect, matching core.run's
    drain discipline. The first SIGTERM runs `on_drain` (when given)
    and stops the serve loop; a second SIGTERM force-exits through
    DrainSignal's SystemExit(143) path."""
    from .core import DrainSignal

    stop = threading.Event()

    def drain() -> bool:
        if on_drain is not None:
            try:
                on_drain()
            except Exception:  # noqa: BLE001 — drain is best-effort
                log.warning("drain hook failed", exc_info=True)
        stop.set()
        return True

    sig = DrainSignal(drain, what=what).install()
    code = 0
    try:
        while not stop.is_set():
            stop.wait(poll_s)
    except KeyboardInterrupt:
        pass
    finally:
        sig.uninstall()
        server.shutdown()
    if sig.draining.is_set():
        code = 143
    return code
