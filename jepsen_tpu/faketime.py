"""libfaketime wrappers: make a DB binary's clock run offset and at a
different rate (reference: jepsen.faketime, faketime.clj:1-31)."""

from __future__ import annotations

import logging

from .control import Remote
from .control.util import exists

log = logging.getLogger("jepsen_tpu.faketime")


def script(cmd: str, init_offset: float, rate: float) -> str:
    """A shell script invoking cmd under faketime with an initial offset
    (seconds) and clock rate (faketime.clj:8-18)."""
    off = int(init_offset)
    sign = "-" if off < 0 else "+"
    return (
        "#!/bin/bash\n"
        f'faketime -m -f "{sign}{abs(off)}s x{float(rate)}" {cmd} "$@"\n'
    )


def wrap(remote: Remote, node, cmd: str, init_offset: float, rate: float
         ) -> None:
    """Replace executable cmd with a faketime wrapper, keeping the
    original at cmd.no-faketime; idempotent (faketime.clj:20-31)."""
    orig = f"{cmd}.no-faketime"
    wrapper = script(orig, init_offset, rate)
    # DB executables are normally root-owned; these must run as root
    # like the reference's su context (faketime.clj:20-31).
    if exists(remote, node, orig):
        log.info("Installing faketime wrapper.")
        remote.exec(node, ["tee", cmd], stdin=wrapper, sudo=True)
        # re-chmod: a prior install may have died before its chmod
        remote.exec(node, ["chmod", "a+x", cmd], sudo=True)
    else:
        remote.exec(node, ["mv", cmd, orig], sudo=True)
        remote.exec(node, ["tee", cmd], stdin=wrapper, sudo=True)
        remote.exec(node, ["chmod", "a+x", cmd], sudo=True)
