"""First-launch calibration of the pallas-vs-native batch routing.

The r5 routing policy hard-coded ``PALLAS_BATCH_MIN = 8192`` — the lane
count where the pallas batch engine's end-to-end wall first beat the
C++ engine on ONE specific host (a v5e behind a ~110 ms dispatch
tunnel).  That constant bakes host-specific dispatch latency into
checker policy: on a TPU VM with local dispatch the crossover sits far
lower, and behind a slower tunnel far higher.  This module measures the
terms the crossover actually depends on, once per process, at first
use:

``t_rt``
    the fixed dispatch+fetch round trip of one pallas launch — the
    batch-size-independent intercept of a two-point end-to-end fit.
``per_lane_pallas``
    the pallas engine's marginal cost per (hard, step-capped) lane —
    the slope of the same fit, measured through the REAL
    ``analysis_batch`` path so it includes encode, pack, transfer and
    kernel, not just the kernel.
``per_lane_native``
    the C++ engine's measured wall per identical lane at the same step
    cap.

The model: checking ``L`` hard lanes costs the native engine
``L * per_lane_native`` (sequential, no launch cost) and the pallas
engine ``t_rt + L * per_lane_pallas``.  The crossover is

    batch_min = t_rt / (per_lane_native - per_lane_pallas)

clamped to ``[CAL_MIN, CAL_MAX]``; when the denominator is not positive
the pallas engine never catches up on this host and the threshold
pins to ``CAL_MAX``.  Lanes are synthetic step-capped corrupt register
histories at ``CAL_MAX_STEPS`` (the bench deep lanes' budget) — the
shape that actually escapes native triage.

``batch_min()`` returns None — and the router falls back to the
documented ``PALLAS_BATCH_MIN`` constant — whenever measurement is
impossible or meaningless: no real TPU backend (interpret-mode pallas
must never preempt the C++ engine), no native toolchain to race
against, or a failed measurement.  The result (or the failure) is
cached per-process; ``JEPSEN_TPU_BATCH_MIN`` overrides everything for
operators who already know their crossover.

A successful measurement is also persisted to an **on-disk cache**
(``JEPSEN_TPU_CALIB_CACHE``; default
``~/.cache/jepsen-tpu/calibration.json``; ``off`` disables) stamped
with the backend/device fingerprint, so warm starts — the resident
daemon's AOT bundle as much as repeated one-shot runs — skip the
multi-second re-measurement.  A cache whose fingerprint no longer
matches the running backend is silently ignored and overwritten by the
next measurement: stale economics must never route a verdict.
``_reset_for_tests`` only drops the in-memory cache; tests point the
env var at a scratch file to isolate the disk layer.
"""

from __future__ import annotations

import json
import logging
import os
import random
import threading
import time
from dataclasses import asdict, dataclass

log = logging.getLogger("jepsen_tpu.checker.calibrate")

CAL_MAX_STEPS = 4_000   # step cap per calibration lane — the bench deep
#                         lanes' budget, i.e. the measured hard-tail shape
CAL_LANES_SMALL = 128   # one block: times t_rt + 128 lanes
CAL_LANES_BIG = 1024    # eight blocks: the second point of the fit
CAL_NATIVE_LANES = 16   # native is sequential; a few lanes suffice
CAL_OPS_PER_LANE = 40   # ~48-entry lanes -> the 64-row pad bucket
CAL_MIN = 1024          # never escalate below one thousand-ish lanes —
#                         under that the fit's noise exceeds the signal
CAL_MAX = 1 << 20       # "never": pallas loses at any realistic width

_ENV = "JEPSEN_TPU_BATCH_MIN"
_CACHE_ENV = "JEPSEN_TPU_CALIB_CACHE"

_lock = threading.Lock()
_cached = False
_calibration: "Calibration | None" = None


@dataclass(frozen=True)
class Calibration:
    """One host's measured dispatch economics (seconds)."""

    t_rt: float             # fixed pallas dispatch+fetch round trip
    per_lane_pallas: float  # marginal pallas cost per hard lane
    per_lane_native: float  # native cost per identical lane

    @property
    def batch_min(self) -> int:
        return derive_batch_min(
            self.t_rt, self.per_lane_native, self.per_lane_pallas)


def derive_batch_min(t_rt: float, per_lane_native: float,
                     per_lane_pallas: float,
                     lo: int = CAL_MIN, hi: int = CAL_MAX) -> int:
    """The lane count where `t_rt + L*pallas < L*native`, clamped."""
    margin = per_lane_native - per_lane_pallas
    if margin <= 0:
        return hi
    return max(lo, min(hi, int(t_rt / margin) + 1))


def _corrupt_register_lanes(n_lanes: int, seed: int = 0) -> list:
    """Deterministic synthetic hard lanes: concurrent cas-register
    histories with heavily corrupted reads.  Most refute only after a
    deep search (or step-cap to unknown), so a step-capped run measures
    the engines at the hard-tail shape the router actually routes —
    the same construction as the bench's invalid-heavy/deep lanes
    (tests/helpers.random_register_history), inlined here because the
    package cannot depend on the test tree."""
    from ..history import Op

    lanes = []
    for lane in range(n_lanes):
        rng = random.Random(seed * 100_003 + lane)
        history, t, reg, pending = [], 0, None, {}
        started = 0
        while started < CAL_OPS_PER_LANE or pending:
            p = rng.randrange(4)
            if p in pending:
                f, value, result = pending.pop(p)
                history.append(Op(p, "ok", f, result, time=t))
            elif started < CAL_OPS_PER_LANE:
                started += 1
                if rng.random() < 0.5:
                    f, value = "read", None
                    result = (rng.randrange(5) if rng.random() < 0.3
                              else reg)
                else:
                    f = "write"
                    value = result = rng.randrange(5)
                    reg = value
                history.append(Op(p, "invoke", f, value, time=t))
                pending[p] = (f, value, result)
            t += 1
        for i, o in enumerate(history):
            o.index = i
        lanes.append(history)
    return lanes


# ---------------------------------------------------------------------------
# On-disk cache (satellite of the resident-service work): a measured
# crossover is a property of (backend, device kind, jax build), not of
# one process — persist it, fingerprint-stamped, so warm starts skip
# the re-measurement the same way the AOT bundle skips recompiles.

def cache_path() -> str | None:
    """The calibration cache file, or None when disabled."""
    p = os.environ.get(_CACHE_ENV)
    if p is None:
        p = os.path.join(os.path.expanduser("~"), ".cache",
                         "jepsen-tpu", "calibration.json")
    return None if p.lower() in ("", "0", "off", "none") else p


def device_fingerprint() -> dict:
    """The backend identity a cached measurement is valid for.  Any
    mismatch — different platform, device generation, device count, or
    jax build — marks the cache stale: dispatch economics measured on
    one backend must never route verdicts on another."""
    import jax

    dev = jax.devices()[0]
    return {
        "platform": str(dev.platform),
        "device_kind": str(getattr(dev, "device_kind", dev)),
        "device_count": int(jax.device_count()),
        "jax": str(jax.__version__),
    }


def _load_disk_cache() -> Calibration | None:
    """A fingerprint-fresh cached Calibration, or None (missing,
    unparseable, or stale — all equally a miss)."""
    p = cache_path()
    if not p:
        return None
    try:
        with open(p) as f:
            rec = json.load(f)
        if rec.get("fingerprint") != device_fingerprint():
            log.info("calibration cache %s is stale for this backend; "
                     "remeasuring", p)
            return None
        c = rec["calibration"]
        return Calibration(float(c["t_rt"]), float(c["per_lane_pallas"]),
                           float(c["per_lane_native"]))
    except Exception:  # noqa: BLE001 — a bad cache is just a miss
        log.debug("calibration cache unreadable", exc_info=True)
        return None


def _update_disk_cache(**fields) -> None:
    """Merge `fields` into the on-disk record, keeping other sections
    (the batch-min calibration and the mesh crossover share one
    fingerprint-stamped file). A stale or torn record is replaced
    wholesale."""
    p = cache_path()
    if not p:
        return
    try:
        from .. import store

        rec: dict = {}
        try:
            with open(p) as f:
                old = json.load(f)
            if (isinstance(old, dict)
                    and old.get("fingerprint") == device_fingerprint()):
                rec = old
        except (OSError, ValueError):
            pass
        rec["fingerprint"] = device_fingerprint()
        rec.update(fields)
        store.atomic_write_json(p, rec)
    except Exception:  # noqa: BLE001 — persistence is best-effort
        log.debug("couldn't persist calibration cache", exc_info=True)


def _save_disk_cache(cal: Calibration) -> None:
    _update_disk_cache(calibration=asdict(cal))


def _measure() -> Calibration | None:
    """Run the actual measurement.  Only called on a real TPU backend
    with a working native toolchain (gated by batch_min)."""
    from ..history import entries as make_entries
    from ..models import CASRegister
    from ..models import jit as mjit
    from ..ops import wgl_native, wgl_pallas_vec

    model = CASRegister(None)
    ess = [make_entries(h)
           for h in _corrupt_register_lanes(CAL_LANES_BIG, seed=7)]
    if not wgl_pallas_vec.batch_eligible(mjit.for_model(model), ess):
        return None

    def pallas_wall(lanes: int) -> float:
        t0 = time.perf_counter()
        wgl_pallas_vec.analysis_batch(
            model, ess[:lanes], max_steps=CAL_MAX_STEPS)
        return time.perf_counter() - t0

    # warm the trace/compile caches so the fit measures dispatch, not
    # the one-time Mosaic compile (which production pays anyway)
    pallas_wall(CAL_LANES_SMALL)
    t_small = min(pallas_wall(CAL_LANES_SMALL) for _ in range(2))
    t_big = pallas_wall(CAL_LANES_BIG)
    per_lane_pallas = max(
        0.0, (t_big - t_small) / (CAL_LANES_BIG - CAL_LANES_SMALL))
    t_rt = max(0.0, t_small - CAL_LANES_SMALL * per_lane_pallas)

    t0 = time.perf_counter()
    for es in ess[:CAL_NATIVE_LANES]:
        wgl_native.analysis(model, es, max_steps=CAL_MAX_STEPS)
    per_lane_native = (time.perf_counter() - t0) / CAL_NATIVE_LANES
    return Calibration(t_rt, per_lane_pallas, per_lane_native)


def calibration() -> Calibration | None:
    """The per-process cached measurement (None when unavailable)."""
    global _cached, _calibration
    if _cached:
        return _calibration
    with _lock:
        if _cached:
            return _calibration
        cal = None
        try:
            import jax

            if jax.devices()[0].platform == "tpu":
                from ..ops import wgl_native
                from . import supervisor as sup_mod

                cal = _load_disk_cache()
                if cal is not None:
                    log.info(
                        "calibration cache hit: batch_min=%d "
                        "(skipping re-measurement)", cal.batch_min)
                    _calibration, _cached = cal, True
                    return _calibration
                sup = sup_mod.get()
                if not (sup.healthy("pallas") and sup.healthy("native")):
                    # a quarantined entrant can't race fairly (or at
                    # all) — skip to the constant fallback rather than
                    # measure a crossover against a sick engine
                    raise RuntimeError("engine quarantined")
                wgl_native._get_lib()  # no native engine: nothing to
                #                        race — constant fallback
                cal = _measure()
                if cal is not None:
                    _save_disk_cache(cal)
                    log.info(
                        "calibrated pallas crossover: t_rt=%.1fms "
                        "pallas=%.3fms/lane native=%.3fms/lane -> "
                        "batch_min=%d", cal.t_rt * 1e3,
                        cal.per_lane_pallas * 1e3,
                        cal.per_lane_native * 1e3, cal.batch_min)
        except Exception:  # noqa: BLE001 — calibration must never fail
            #             a check; the constant fallback is always sound
            log.debug("pallas crossover calibration failed", exc_info=True)
            cal = None
        _calibration = cal
        _cached = True
    return _calibration


def batch_min() -> int | None:
    """The measured pallas escalation threshold, or None for "use the
    documented constant".  ``JEPSEN_TPU_BATCH_MIN`` pins it outright
    (read per call so tests and operators can flip it live)."""
    env = os.environ.get(_ENV)
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            log.warning("ignoring non-integer %s=%r", _ENV, env)
    cal = calibration()
    return None if cal is None else cal.batch_min


# ---------------------------------------------------------------------------
# Mesh-vs-single crossover (the pod-scale rungs' routing bar).
#
# The closure mesh rung pays one all-gather of the packed bitmat per
# squaring round for a D-way split of the matmul; the WGL mesh rung
# pays per-device dispatch + empty-lane chunk padding for a D-way
# split of the lane pack. Both only win past a size bar, and like
# batch_min that bar is a property of the backend, not of policy —
# so it's measured (closure, on real multi-device TPU backends, and
# persisted next to the batch-min record) or derived from the device
# count (lanes), with env pins for operators who know their mesh.

MESH_MIN_N_DEFAULT = 2048    # closure: adjacency side where block-row
#                              sharding starts winning (conservative —
#                              below it one chip's matmul is cheap and
#                              the all-gather dominates)
MESH_LANES_MIN_DEFAULT = 64  # wgl: fewer lanes than this aren't worth
#                              dealing even on wide meshes
MESH_NEVER = 1 << 30         # "mesh never wins on this backend"
MESH_CAL_SIZES = (512, 2048)  # measured closure sizes (pow2 buckets)

_ENV_MESH_N = "JEPSEN_TPU_MESH_MIN_N"
_ENV_MESH_LANES = "JEPSEN_TPU_MESH_LANES_MIN"

_mesh_cached = False
_mesh_min_n: int | None = None  # measured; None = unmeasured/failed


def _measure_mesh_min_n() -> int | None:
    """Time single-device vs mesh closure at MESH_CAL_SIZES; the
    crossover is the smallest measured size where the mesh wall wins,
    MESH_NEVER when it never does. Both paths warm first so the race
    measures steady-state launches, not compiles."""
    import numpy as np

    import jax

    from ..ops import closure_tpu

    devices = jax.devices()
    if len(devices) < 2:
        return None

    def wall(fn) -> float:
        fn()  # warm: compile + first launch
        t0 = time.perf_counter()
        fn()
        return time.perf_counter() - t0

    for n in MESH_CAL_SIZES:
        a = np.random.default_rng(11).random((n, n)) < (2.0 / n)
        t_single = wall(lambda: closure_tpu.reach_batch([a]))
        t_mesh = wall(
            lambda: closure_tpu.reach_batch([a], devices=devices))
        if t_mesh <= t_single:
            return n
    return MESH_NEVER


def mesh_min_n() -> int:
    """The smallest adjacency side the closure_mesh rung should take.
    ``JEPSEN_TPU_MESH_MIN_N`` pins it; otherwise measured once per
    process on real multi-device TPU backends (disk-cached, stamped
    with the same fingerprint as the batch-min record); otherwise the
    documented conservative default."""
    global _mesh_cached, _mesh_min_n
    env = os.environ.get(_ENV_MESH_N)
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            log.warning("ignoring non-integer %s=%r", _ENV_MESH_N, env)
    if not _mesh_cached:
        with _lock:
            if not _mesh_cached:
                v = None
                try:
                    import jax

                    if (jax.devices()[0].platform == "tpu"
                            and jax.device_count() >= 2):
                        v = _load_disk_mesh()
                        if v is None:
                            v = _measure_mesh_min_n()
                            if v is not None:
                                _update_disk_cache(mesh_min_n=v)
                                log.info("calibrated mesh crossover: "
                                         "mesh_min_n=%d", v)
                except Exception:  # noqa: BLE001 — never fail a check
                    log.debug("mesh crossover calibration failed",
                              exc_info=True)
                    v = None
                _mesh_min_n, _mesh_cached = v, True
    return _mesh_min_n if _mesh_min_n is not None else MESH_MIN_N_DEFAULT


def _load_disk_mesh() -> int | None:
    p = cache_path()
    if not p:
        return None
    try:
        with open(p) as f:
            rec = json.load(f)
        if rec.get("fingerprint") != device_fingerprint():
            return None
        v = rec.get("mesh_min_n")
        return int(v) if v is not None else None
    except Exception:  # noqa: BLE001 — a bad cache is just a miss
        return None


def mesh_lanes_min() -> int:
    """The smallest lane batch the wgl_mesh rung should take:
    ``JEPSEN_TPU_MESH_LANES_MIN`` or a few chunks per device (the
    dealing is cheap; the bar only filters batches whose chunks would
    be mostly empty-lane padding)."""
    env = os.environ.get(_ENV_MESH_LANES)
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            log.warning("ignoring non-integer %s=%r", _ENV_MESH_LANES,
                        env)
    try:
        import jax

        n_dev = jax.device_count()
    except Exception:  # noqa: BLE001 — no usable backend
        n_dev = 1
    return max(MESH_LANES_MIN_DEFAULT, 4 * n_dev)


def measured_mesh_min_n() -> int | None:
    """The measured (or seeded) mesh crossover, None when this
    process never measured one — what the AOT bundle persists (the
    default fallback is policy, not a measurement)."""
    mesh_min_n()
    return _mesh_min_n


def seed_mesh(v: int | None) -> None:
    """Install a previously-measured mesh crossover (the AOT bundle's
    warm-start path, mirroring seed())."""
    global _mesh_cached, _mesh_min_n
    with _lock:
        _mesh_min_n = None if v is None else int(v)
        _mesh_cached = True


def seed(cal: Calibration | None) -> None:
    """Install a previously-measured Calibration as this process's
    cached measurement without re-measuring — the AOT engine bundle's
    warm-start path (jepsen_tpu/serve/bundle.py), which persists the
    calibration next to the compile-cache manifest. Callers are
    responsible for freshness (the bundle's fingerprint check)."""
    global _cached, _calibration
    with _lock:
        _calibration = cal
        _cached = True


def _reset_for_tests() -> None:
    """Drop the in-memory cache (test hook). The on-disk cache is NOT
    touched — tests isolate it by pointing JEPSEN_TPU_CALIB_CACHE at a
    scratch file (or "off")."""
    global _cached, _calibration, _mesh_cached, _mesh_min_n
    with _lock:
        _cached = False
        _calibration = None
        _mesh_cached = False
        _mesh_min_n = None
