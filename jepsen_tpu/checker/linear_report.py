"""Counterexample rendering: when a linearizability check fails, draw
the concurrency window around the failing operation as linear.svg
(reference: knossos.linear.report/render-analysis!, invoked at
checker.clj:130-137 — "Writing linearizability analysis").

The picture: one lane per process, each op a bar spanning its
invoke→complete interval, labeled "f value". The op at whose return the
search died is red; ops in the deepest legal linearization found are
numbered with their order, so the reader can see exactly how far a
legal history got and which completion it could not absorb. Pure-string
SVG, no plotting dependencies."""

from __future__ import annotations

import html
from ..history import Op, pairs as history_pairs

MAX_OPS = 40          # window cap, like the reference's truncation
LANE_H = 34
BAR_H = 22
LEFT_PAD = 90
RIGHT_PAD = 24
TOP_PAD = 46
PX_PER_COL = 46

OK_FILL = "#81bfd1"
CRASH_FILL = "#c6a6d1"
FAIL_FILL = "#e06c5f"
LIN_STROKE = "#2a7a34"


def _pairs(history: list) -> list:
    """(invoke, completion|None) pairs, in invoke order."""
    return [(p.invoke, p.completion) for p in history_pairs(history)]


def _window(history: list, failing: Op | None) -> list:
    """The (invoke, completion) pairs concurrent with the failure,
    capped at MAX_OPS. Without a known failing op, the tail of the
    history."""
    pairs = _pairs(history)
    if failing is None:
        return pairs[-MAX_OPS:]
    # locate the failing op's pair: exact index match wins outright —
    # a loose (process, f) match could center the window on a later
    # unrelated op and leave the real failure outside the picture
    fail_pos = None
    for i, (inv, comp) in enumerate(pairs):
        if failing.index is not None and (
            inv.index == failing.index
            or (comp is not None and comp.index == failing.index)
        ):
            fail_pos = i
            break
    if fail_pos is None:  # no index info: last (process, f, value) match
        for i, (inv, comp) in enumerate(pairs):
            if (inv.process == failing.process and inv.f == failing.f
                    and inv.value == failing.value):
                fail_pos = i
    if fail_pos is None:
        return pairs[-MAX_OPS:]
    lo = max(0, fail_pos - MAX_OPS // 2)
    return pairs[lo:lo + MAX_OPS]


def _is_failing(inv: Op, comp: Op | None, failing: Op | None) -> bool:
    if failing is None:
        return False
    for o in (inv, comp):
        if o is not None and o.index is not None \
                and o.index == failing.index:
            return True
    return False


def _lin_order(window: list, best: list | None) -> dict:
    """Map window position -> 1-based order in the deepest legal
    linearization."""
    if not best:
        return {}
    order = {}
    used = set()
    for rank, lin_op in enumerate(best, start=1):
        for i, (inv, comp) in enumerate(window):
            if i in used:
                continue
            if inv.process == lin_op.process and inv.f == lin_op.f \
                    and inv.value == lin_op.value:
                order[i] = rank
                used.add(i)
                break
    return order


def _label(inv: Op, comp: Op | None) -> str:
    value = inv.value
    if comp is not None and comp.value is not None:
        value = comp.value
    s = f"{inv.f} {value}" if value is not None else str(inv.f)
    return s if len(s) <= 18 else s[:17] + "…"


def render_analysis(history: list, result: dict, path: str) -> str | None:
    """Write linear.svg for an invalid linearizability result
    ({"op": ..., "final_paths": [[...]]}) to `path`. Returns the path,
    or None when there is nothing to draw."""
    history = [o for o in history if o.process != "nemesis"]
    if not history:
        return None
    failing = None
    if result.get("op"):
        failing = Op.from_dict(result["op"])
    best = None
    if result.get("final_paths"):
        best = [Op.from_dict(d) for d in result["final_paths"][0]]

    window = _window(history, failing)
    if not window:
        return None
    lin = _lin_order(window, best)

    processes = sorted({inv.process for inv, _ in window},
                       key=lambda p: (isinstance(p, str), p))
    lane = {p: i for i, p in enumerate(processes)}

    width = LEFT_PAD + PX_PER_COL * len(window) + RIGHT_PAD
    height = TOP_PAD + LANE_H * len(processes) + 30

    parts = [
        f'<svg xmlns="http://www.w3.org/2000/svg" width="{width}" '
        f'height="{height}" font-family="Helvetica, Arial, sans-serif" '
        'font-size="11">',
        f'<rect width="{width}" height="{height}" fill="white"/>',
        '<text x="8" y="18" font-size="13" font-weight="bold">'
        "Linearizability failure window</text>",
        '<text x="8" y="34" fill="#666">red = op the search could not '
        "linearize; green numbers = deepest legal order found</text>",
    ]
    for p in processes:
        y = TOP_PAD + lane[p] * LANE_H + BAR_H // 2 + 4
        parts.append(
            f'<text x="8" y="{y}" fill="#333">process '
            f"{html.escape(str(p))}</text>"
        )

    for i, (inv, comp) in enumerate(window):
        x = LEFT_PAD + i * PX_PER_COL
        y = TOP_PAD + lane[inv.process] * LANE_H
        # bar spans from its column to its completion's column
        end = i
        if comp is not None:
            # find how many window invocations started before completion
            for j, (inv2, _) in enumerate(window):
                if inv2.time is not None and comp.time is not None \
                        and inv2.time <= comp.time:
                    end = j
        w = max(PX_PER_COL - 6, (end - i) * PX_PER_COL + PX_PER_COL - 6)
        if _is_failing(inv, comp, failing):
            fill = FAIL_FILL
        elif comp is None or comp.type == "info":
            fill = CRASH_FILL
        else:
            fill = OK_FILL
        stroke = (f' stroke="{LIN_STROKE}" stroke-width="2"'
                  if i in lin else "")
        parts.append(
            f'<rect x="{x}" y="{y}" width="{w}" height="{BAR_H}" '
            f'rx="4" fill="{fill}"{stroke}/>'
        )
        parts.append(
            f'<text x="{x + 4}" y="{y + 15}" fill="#111">'
            f"{html.escape(_label(inv, comp))}</text>"
        )
        if i in lin:
            parts.append(
                f'<text x="{x + 2}" y="{y - 3}" fill="{LIN_STROKE}" '
                f'font-weight="bold">{lin[i]}</text>'
            )
    parts.append("</svg>")

    with open(path, "w") as f:
        f.write("\n".join(parts))
    return path
