"""Recovery verification (the OmniLink lesson, PAPERS.md): every
injected fault must be provably revoked before analysis, or checker
verdicts conflate system bugs with harness residue.

RecoveryChecker audits the history against the fault families recorded
by nemesis.combined (test["fault_families"], or the ctor arg): for each
family whose heals set is non-empty, the LAST fault op must be followed
by a heal op whose completion carries no error; and once the final heal
lands, the post-heal window must contain at least min_ok successful
client ops — proof the cluster actually served traffic again. Families
with an empty heals set (file corruption) are exempt from the healed
audit: their faults are not revocable by design.
"""

from __future__ import annotations

from . import Checker

NEMESIS_PROCESS = "nemesis"


class RecoveryChecker(Checker):
    def __init__(self, families: dict | None = None, min_ok: int = 1):
        self.families = families
        self.min_ok = min_ok

    def check(self, test, history, opts=None) -> dict:
        families = (self.families if self.families is not None
                    else test.get("fault_families") or {})
        history = list(history)
        # positions, not op.index: this must also work on histories that
        # were never run through index()
        nem = [(i, o) for i, o in enumerate(history)
               if o.process == NEMESIS_PROCESS]

        unhealed: dict = {}
        faults_seen: dict = {}
        heal_fs: set = set()
        audited_any = False
        for fam, spec in families.items():
            fault_set = set(spec.get("faults") or ())
            heals = set(spec.get("heals") or ())
            heal_fs |= heals
            fault_positions = [i for i, o in nem if o.f in fault_set]
            faults_seen[fam] = len(fault_positions)
            if not fault_positions:
                continue  # family never fired; nothing to audit
            if not heals:
                continue  # unrevokable by design (corruption)
            audited_any = True
            heal_entries = [(i, o) for i, o in nem if o.f in heals]
            if not heal_entries:
                unhealed[fam] = "no heal op in history"
                continue
            last_heal_i, last_heal = heal_entries[-1]
            if last_heal_i < fault_positions[-1]:
                unhealed[fam] = "fault op after the last heal"
            elif last_heal.error is not None:
                unhealed[fam] = f"final heal errored: {last_heal.error}"

        # the stability audit: ok client ops after the final heal of ANY
        # audited family (both journal entries of that heal)
        heal_positions = [i for i, o in nem if o.f in heal_fs]
        post_heal_ok = None
        if audited_any and heal_positions:
            cutoff = heal_positions[-1]
            post_heal_ok = sum(
                1 for o in history[cutoff + 1:]
                if isinstance(o.process, int) and o.is_ok)
            if post_heal_ok < self.min_ok:
                unhealed["stability"] = (
                    f"only {post_heal_ok} ok client ops after the final "
                    f"heal (need >= {self.min_ok})")

        return {
            "valid": not unhealed,
            "unhealed": unhealed,
            "faults_seen": faults_seen,
            "post_heal_ok_count": post_heal_ok,
        }


def recovery(families: dict | None = None, min_ok: int = 1
             ) -> RecoveryChecker:
    return RecoveryChecker(families=families, min_ok=min_ok)
