"""Linearizability checker (reference: jepsen.checker/linearizable,
checker.clj:116-141, backed by knossos; SURVEY.md SS3.2).

Backends:
  "host"        ops/wgl_host.py — Python bitset-DFS with memo cache
                (knossos.wgl analog).
  "tpu"         ops/wgl_tpu.py — jitted bitmask-DFS kernel, vmapped over
                keys, memo cache in HBM. Requires a model with an int32
                encoding (models/jit.py) and payloads that fit int32.
  "pallas"      ops/wgl_pallas_vec.py — the whole search as ONE Mosaic
                kernel, 128 lanes vectorized per program. Scalar
                models plus both queue families; the fastest batch
                engine by far and the end-to-end winner at >=8k-lane
                shapes (the measured crossover lives in bench.py's
                tpu-vs-native lanes).
  "linear"      ops/linear.py — just-in-time linearization over
                configurations (knossos.linear analog): a genuinely
                different algorithm, a single in-order sweep carrying
                all reachable (state, early-linearized) configurations.
  "competition" linear raced against WGL (tpu when eligible, host
                otherwise), first definite verdict wins — two distinct
                algorithms, like knossos.competition racing
                linear/analysis vs wgl/analysis (checker.clj:125-127).
  "native"      ops/wgl_native.py — the C++ engine (same algorithm and
                search order as host, GIL-free, ~20x steps/sec);
                compiled on first use, needs a model with an int32
                encoding.
  "auto"        single history: native when it builds (measured
                fastest for one sequential search: per-kernel-launch
                overhead means the TPU only wins on BATCHES), else
                tpu when eligible, else host. Batched (check_batch,
                used by the independent checker): a cheap native
                triage resolves the easy lanes, and the hard tail
                escalates to the pallas batch kernel — the shape the
                TPU demonstrably wins. The escalation bar is a
                per-process MEASURED dispatch crossover
                (checker/calibrate.py), not a constant.

Like the reference, detailed failure artifacts are truncated (the full
set "can take *hours*" to write, checker.clj:138-141).
"""

from __future__ import annotations

import atexit
import os
import threading
from typing import Any

# Threads abandoned by competition races (the losing search keeps
# running). They must be joined before interpreter exit: a daemon thread
# killed mid-XLA-compile aborts the process with "FATAL: exception not
# rethrown".
_abandoned_racers: list = []


@atexit.register
def _drain_racers():
    import time as _t

    deadline = _t.monotonic() + 120  # one shared bound, however many races
    for t in _abandoned_racers:
        t.join(timeout=max(0.0, deadline - _t.monotonic()))

from ..history import entries as make_entries
from ..models import Model
from ..ops import linear as linear_mod
from ..ops import wgl_host
from . import Checker

TRUNCATE = 10

# Batched-auto policy (measured on the v5e, BENCH tpu-vs-native lanes):
# the native engine triages each lane with a small step budget first
# (~8-10M steps/s, no launch latency — a typical valid per-key lane
# resolves in well under a millisecond) and then finishes the
# unresolved tail with the full budget. The pallas lane kernel beats
# native kernel-resident (~80M steps/s across 128 lanes vs ~10M
# single-thread), but the tunnel-attached host's fixed dispatch+fetch
# round trip (~110ms) sets an end-to-end floor native undercuts at
# SMALL shapes (34-1024 lanes are round-trip-bound outright;
# deep-4096 native still leads ~540 vs ~620ms). The r5 chunked
# pipelined launches moved the crossover onto this host: deep-8192 is
# parity and deep-16384 the pallas engine WINS end-to-end (~1.0s vs
# ~1.4s, non-overlapping spreads — BENCH r5 tpu-vs-native). So auto
# escalates a hard tail to pallas either when native is UNAVAILABLE
# (e.g. a TPU VM without a compiler; pallas beats the pure-Python
# fallback >10x) or when the tail is at least PALLAS_BATCH_MIN lanes
# — the measured shape where the kernel beats the C++ engine outright.
#
# The escalation bar itself is MEASURED per process at first use
# (checker/calibrate.py fits t_rt + L*per_lane_pallas vs
# L*per_lane_native through the real engine paths and derives the
# crossover); PALLAS_BATCH_MIN is the documented FALLBACK for hosts
# where calibration is unavailable — no real TPU, no native toolchain
# to race, or a failed measurement — frozen at the r5 value measured
# on the tunnel-attached v5e. JEPSEN_TPU_BATCH_MIN overrides both.
TRIAGE_MAX_STEPS = 2_000
PALLAS_BATCH_MIN = 8192

# Explicit-algorithm degradation ladders (checker/supervisor.py): a
# failed or quarantined engine demotes to the next rung rather than
# aborting the check — every rung computes identical verdicts (pinned
# by the parity corpus), so a demoted verdict is still THE verdict.
_LADDERS = {
    "pallas": ("pallas", "tpu", "native", "host"),
    "tpu": ("tpu", "native", "host"),
    "native": ("native", "host"),
    "host": ("host",),
    # linear is a different algorithm; its verdicts still agree, so the
    # host WGL search is a sound floor for it too
    "linear": ("linear", "host"),
}


def _pallas_batch_min() -> int:
    """The batched-auto escalation bar: the calibrated crossover when
    the per-process measurement exists, else PALLAS_BATCH_MIN (read at
    call time so tests and operators can repoint the module global)."""
    from . import calibrate

    bm = calibrate.batch_min()
    return PALLAS_BATCH_MIN if bm is None else bm


def _tpu_backend() -> bool:
    """Is the default jax backend a REAL TPU? The PALLAS_BATCH_MIN
    escalation was measured on hardware; on a CPU-only host the pallas
    engine runs interpret-mode emulation, which must never preempt the
    C++ engine."""
    try:
        import jax

        return jax.devices()[0].platform == "tpu"
    except Exception:  # noqa: BLE001 — no jax / no backend
        return False


def _pallas_eligible(model, entries_list) -> bool:
    from ..models import jit as mjit

    try:
        from ..ops import wgl_pallas_vec
    except ImportError:
        return False
    jm = mjit.for_model(model)
    if jm is None:
        return False
    return wgl_pallas_vec.batch_eligible(jm, entries_list)


def _native_available(model, es) -> bool:
    """The C++ engine can take this history AND its library builds."""
    try:
        from ..ops import wgl_native

        if not wgl_native.eligible(model, es):
            return False
        wgl_native._get_lib()
        return True
    except Exception:  # noqa: BLE001
        return False


def _tpu_eligible(model, es) -> bool:
    from ..models import jit as mjit

    try:
        from ..ops import wgl_tpu  # noqa: F401
    except ImportError:
        return False
    jm = mjit.for_model(model)
    if jm is None:
        return False
    # per-model payload check: int32-encodable for scalar models,
    # hashable for the queue's per-lane slot map (models/jit.py)
    return jm.lane_eligible(es)


def _combine_lanes(rs: list):
    """One WGLResult for a P-compositionally decomposed history: valid
    iff every lane is (locality — ops/pcomp.py); an invalid lane's
    counterexample is the history's counterexample (its ops are real
    ops of the full history); step counts sum. An unknown lane's error
    tag (e.g. "deadline" from a budget-expired chunk) survives the
    combine so callers can tell WHY the verdict degraded."""
    steps = sum(getattr(r, "steps", 0) or 0 for r in rs)
    for r in rs:
        if r.valid is False:
            return wgl_host.WGLResult(
                valid=False, op=r.op,
                best_linearization=r.best_linearization, steps=steps)
    if any(r.valid == "unknown" for r in rs):
        out = wgl_host.WGLResult(valid="unknown", steps=steps)
        for r in rs:
            if r.valid == "unknown" and getattr(r, "error", None):
                out.error = r.error  # type: ignore[attr-defined]
                break
        return out
    return wgl_host.WGLResult(valid=True, steps=steps)


class Linearizable(Checker):
    def __init__(
        self,
        model: Model | None = None,
        algorithm: str = "auto",
        time_limit: float | None = None,
    ):
        self.model = model
        self.algorithm = algorithm
        self.time_limit = time_limit

    def _model(self, test) -> Model:
        m = self.model or (test or {}).get("model")
        if m is None:
            raise ValueError("linearizable checker needs a model")
        return m

    @staticmethod
    def _budget(test):
        """The caller's absolute-monotonic verdict budget, when one is
        stamped on the test (`test["deadline"]` — the serve daemon's
        deadline_ms plumbing and the watch window budget). None on the
        default contract, keeping every no-deadline path bit-identical
        to before budgets existed."""
        b = (test or {}).get("deadline")
        return None if b is None else float(b)

    def check(self, test, history, opts=None) -> dict:
        from . import supervisor as sup_mod

        sup = sup_mod.get()
        snap0 = sup.telemetry.snapshot()
        model = self._model(test)
        budget = self._budget(test)
        history = list(history)  # may be a one-shot iterator; used twice
        es = make_entries(history)
        algorithm = self.algorithm
        if algorithm == "auto":
            # P-compositional fast path: a product-model history
            # (unordered queue by value, single-key-txn multi-register
            # by key — the Model.components hook decides, ops/pcomp.py)
            # decomposes into micro-lanes and the exponential
            # interleaving search collapses into a batch of trivial
            # ones.
            from ..ops import pcomp

            if pcomp.eligible(model):
                lanes = pcomp.split(model, es)
                if lanes is not None:
                    rs = self._component_results(
                        lanes, self._steps_budget(),
                        deadline=self._deadline(), budget=budget)
                    d = self._result(_combine_lanes(rs))
                    self._attach_supervision(d, sup, snap0)
                    self._render_invalid(test, history, d, opts)
                    return d
            # for ONE history the sequential C++ engine wins outright:
            # a TPU kernel launch costs more than most whole searches,
            # and a single lane can't amortize it (BENCH_r03
            # tpu-vs-native). The TPU earns its keep in check_batch.
            # A quarantined native engine is skipped outright — the
            # ladder below would demote anyway, but not attempting it
            # is the breaker's whole point.
            if sup.healthy("native") and _native_available(model, es):
                algorithm = "native"
            elif _tpu_eligible(model, es):
                algorithm = "tpu"
            else:
                algorithm = "host"

        if algorithm in _LADDERS:
            # supervised: deadline watchdog + retry/backoff + breaker +
            # demotion down the ladder; check_safe (the caller's
            # wrapper) still turns a fully-exhausted ladder into an
            # unknown verdict
            (r,) = sup.run(
                model, [es], time_limit=self.time_limit,
                ladder=_LADDERS[algorithm],
                deadline=self._watchdog(sup), budget=budget,
                on_exhausted="raise")
        elif algorithm == "competition":
            d = self._competition(model, es)
            self._attach_supervision(d, sup, snap0)
            self._render_invalid(test, history, d, opts)
            return d
        else:
            raise ValueError(f"unknown algorithm {self.algorithm!r}")
        d = self._result(r)
        self._attach_supervision(d, sup, snap0)
        self._render_invalid(test, history, d, opts)
        return d

    @staticmethod
    def _attach_supervision(d, sup, snap0) -> None:
        """Attach the supervision telemetry this check generated
        (retries, demotions, breaker trips, salvaged chunks...) when
        any fired — a plain healthy call count is not an event and
        stays off the result. Counters are process-global, so
        concurrent checkers may cross-attribute — the field is
        observability, not an invariant."""
        from . import supervisor as sup_mod

        delta = sup_mod.Telemetry.delta(snap0, sup.telemetry.snapshot())
        if any(k != "calls" for k in delta):
            d["supervision"] = delta

    def _watchdog(self, sup) -> float | None:
        """The wall-clock watchdog deadline for one supervised engine
        call: generous slack over time_limit (the engines enforce the
        budget themselves — the watchdog only catches calls that wedge
        BEFORE the kernel can count steps, e.g. a hung compile).
        Without a time_limit the supervisor's call_timeout (if any)
        applies instead."""
        import time as _t

        if self.time_limit is None:
            return None
        c = sup.config
        return (_t.monotonic() + self.time_limit * c.deadline_slack
                + c.deadline_grace)

    def check_batch(self, test, items) -> list[dict]:
        """Check many independent histories in one pass — the batched
        fast path the independent checker routes through. `items` is a
        list of (history, per_item_opts); returns one result dict per
        item, same shape as check().

        The batched "auto" policy is where the TPU earns its keep
        (VERDICT r2 item 2): the C++ engine triages every lane with a
        small step budget first — at ~10M steps/s it clears typical
        valid lanes in microseconds — and the unresolved tail (deep
        searches) escalates to the pallas batch kernel, whose fixed
        launch cost amortizes across exactly that shape (measured
        ~3x native wall-clock on 4k-lane refutation-heavy batches,
        BENCH_r03 tpu-vs-native)."""
        from . import supervisor as sup_mod

        sup = sup_mod.get()
        snap0 = sup.telemetry.snapshot()
        opts_list = [o for _, o in items]
        histories = [list(h) for h, _ in items]
        model = self._model(test)
        ess = [make_entries(h) for h in histories]
        n = len(ess)
        results: list = [None] * n

        def finish(i, r):
            d = self._result(r)
            self._render_invalid(test, histories[i], d, opts_list[i])
            results[i] = d

        def attach_all():
            """One shared telemetry dict on every item of the batch (it
            was ONE supervised pass — per-item attribution would be
            fiction); independent.py dedups by object identity. A plain
            healthy call count is not an event and stays off."""
            delta = sup_mod.Telemetry.delta(
                snap0, sup.telemetry.snapshot())
            if any(k != "calls" for k in delta):
                for d in results:
                    if d is not None:
                        d["supervision"] = delta

        algorithm = self.algorithm
        budget = self._budget(test)
        batch_kw = self._steps_budget()
        if algorithm in ("pallas", "tpu"):
            # supervised batch: a mid-batch engine failure demotes the
            # affected chunk down the ladder and salvages the rest —
            # never aborts the whole batch (on_exhausted="unknown")
            for i, r in enumerate(sup.run(
                    model, ess, ladder=_LADDERS[algorithm],
                    deadline=self._watchdog(sup), budget=budget,
                    on_exhausted="unknown", **batch_kw)):
                finish(i, r)
            attach_all()
            return results
        if algorithm != "auto":
            # host/native/linear/competition: per-lane, same as check()
            for i, (h, o) in enumerate(zip(histories, opts_list)):
                results[i] = self.check(test, h, o)
            return results

        # P-compositional preprocessing: product-model histories
        # decompose into micro-lanes via the Model.components hook
        # (ops/pcomp.py); the whole batch's lanes flatten into ONE
        # engine pass per distinct sub-model and each item's verdict
        # recombines from its own lanes.
        from ..ops import pcomp

        if pcomp.eligible(model):
            flat: list = []
            spans: list = []
            ok = True
            for es in ess:
                lanes = pcomp.split(model, es)
                if lanes is None:
                    ok = False
                    break
                spans.append((len(flat), len(flat) + len(lanes)))
                flat.extend(lanes)
            if ok:
                rs = self._component_results(flat, batch_kw,
                                             deadline=self._deadline(),
                                             budget=budget)
                for i, (a, b) in enumerate(spans):
                    finish(i, _combine_lanes(rs[a:b]))
                attach_all()
                return results

        for i, r in enumerate(self._auto_results(model, ess, batch_kw,
                                                 budget=budget)):
            finish(i, r)
        attach_all()
        return results

    def _steps_budget(self) -> dict:
        """time_limit translated to a per-engine-call step budget (a
        while-loop kernel can't consult the wall clock, so the budget
        becomes steps via a conservative rate estimate — the same
        translation wgl_tpu.analysis applies)."""
        if self.time_limit is None:
            return {}
        from ..ops import wgl_tpu as _wt

        return {"max_steps": max(
            1000, int(self.time_limit * _wt.STEPS_PER_SEC_ESTIMATE))}

    def _deadline(self):
        """A wall-clock deadline for decomposed-lane passes: the lanes
        of ONE logical check share ONE time_limit (per-lane limits
        would multiply the caller's budget by the lane count)."""
        import time as _t

        return (None if self.time_limit is None
                else _t.monotonic() + self.time_limit)

    def _component_results(self, comp_lanes, batch_kw,
                           deadline: float | None = None,
                           budget: float | None = None) -> list:
        """WGLResults for a flat list of (sub_model, Entries) component
        lanes (pcomp.split output), batched per DISTINCT sub-model —
        the engines take one model per batch call (grouping shared
        with the serve daemon's cross-run packer via
        pcomp.group_lanes)."""
        from ..ops import pcomp

        out: list = [None] * len(comp_lanes)
        for m, idxs in pcomp.group_lanes(comp_lanes).items():
            rs = self._auto_results(
                m, [comp_lanes[i][1] for i in idxs], batch_kw,
                deadline=deadline, budget=budget)
            for i, r in zip(idxs, rs):
                out[i] = r
        return out

    def _auto_results(self, model, ess, batch_kw,
                      deadline: float | None = None,
                      budget: float | None = None) -> list:
        """The batched "auto" engine policy as raw WGLResults: batches
        at/past the measured pallas crossover go straight to the
        pallas engine; below it, native triage + native finish, with
        the hard tail escalating to pallas when it clears the same bar
        (policy rationale at TRIAGE_MAX_STEPS / _pallas_batch_min
        above). Native availability is PER LANE — a single lane with
        (say) a payload outside int32 must not derail the rest of the
        batch.
        The C++ engine is stateless per call and ctypes drops the GIL
        for its duration, so on multi-core control nodes lanes fan out
        over a thread pool (the reference's bounded-pmap per-key
        checking, independent.clj:269-287)."""
        from . import supervisor as sup_mod

        sup = sup_mod.get()
        n = len(ess)
        bm = _pallas_batch_min()
        # watchdog for supervised calls: the shared deadline plus grace
        # (the engines honor the deadline themselves via budgets; the
        # watchdog only catches calls wedged before they can count)
        import time as _t

        wd = (None if deadline is None
              else deadline + sup.config.deadline_grace)
        if (n >= bm and _tpu_backend() and sup.healthy("pallas")
                and _pallas_eligible(model, ess)):
            # whole-batch fast route: at or past the measured crossover
            # even the TRIAGE pass costs more wall than the pallas
            # round trip it tries to avoid (O(n * TRIAGE_MAX_STEPS)
            # sequential native steps — pcomp micro-lane batches land
            # here by the thousands), and the pallas engine's own
            # two-pass scheduler already plays the triage role
            # in-kernel (PASS1_CAP + dense survivor repack).
            return list(sup.run(
                model, ess, ladder=_LADDERS["pallas"], deadline=wd,
                budget=budget, on_exhausted="unknown", **batch_kw))
        out: list = [None] * n
        if not sup.healthy("native"):
            # quarantined by the breaker: route around it entirely
            native_ok = [False] * n
        else:
            try:
                from ..ops import wgl_native

                wgl_native._get_lib()
                native_ok = [wgl_native.eligible(model, es) for es in ess]
            except Exception:  # noqa: BLE001 — no toolchain / build
                native_ok = [False] * n

        def native_map(idxs, fn):
            """[(i, WGLResult)] for idxs, pooled when it can help."""
            workers = min(len(idxs), os.cpu_count() or 1, 16)
            if workers > 1 and len(idxs) > 1:
                from concurrent.futures import ThreadPoolExecutor

                with ThreadPoolExecutor(max_workers=workers) as pool:
                    return list(zip(idxs, pool.map(fn, idxs)))
            return [(i, fn(i)) for i in idxs]

        def triage_one(i):
            """None means 'triage itself failed' — the lane is not
            resolved AND the native engine takes a health strike."""
            try:
                return wgl_native.analysis(
                    model, ess[i], max_steps=TRIAGE_MAX_STEPS)
            except Exception as e:  # noqa: BLE001
                sup.note_failure("native", e)
                return None

        triage = [i for i in range(n) if native_ok[i]]
        pending = [i for i in range(n) if not native_ok[i]]
        for i, r in native_map(triage, triage_one):
            if r is None or r.valid == "unknown":
                pending.append(i)
            else:
                out[i] = r

        def lane_limit():
            """Per-lane wall limit: the shared deadline's remainder
            when one exists, else the full per-lane time_limit — in
            either case capped by the client budget's remainder."""
            lim = (self.time_limit if deadline is None
                   else max(0.001, deadline - _t.monotonic()))
            if budget is not None:
                rem = max(0.001, budget - _t.monotonic())
                lim = rem if lim is None else min(lim, rem)
            return lim

        hard = [i for i in pending if native_ok[i]]
        rest = [i for i in pending if not native_ok[i]]
        pallas_ok = None  # remembered when it covers `rest` exactly —
        #                   the probe is O(total ops), don't pay twice
        if (len(hard) >= bm
                and _tpu_backend()
                and sup.healthy("pallas")
                and _pallas_eligible(model, [ess[i] for i in hard + rest])):
            # a hard tail this wide is the measured shape where the
            # pallas engine beats the C++ engine END-TO-END (the
            # calibrated crossover, or BENCH r5 deep-16384 via the
            # PALLAS_BATCH_MIN fallback) — escalate it even though
            # native could finish it
            rest = hard + rest
            hard = []
            pallas_ok = True
        if hard:
            # supervised native finish (wgl_native.analysis_batch pools
            # the lanes internally, same fan-out as the old native_map)
            for i, r in zip(hard, sup.run(
                    model, [ess[i] for i in hard],
                    time_limit=lane_limit(), ladder=("native", "host"),
                    deadline=wd, budget=budget,
                    on_exhausted="unknown")):
                out[i] = r
        if rest:
            sub = [ess[i] for i in rest]
            if pallas_ok is None:
                pallas_ok = (sup.healthy("pallas")
                             and _pallas_eligible(model, sub))
            if pallas_ok:
                rs = sup.run(model, sub, ladder=("pallas", "tpu", "host"),
                             deadline=wd, budget=budget,
                             on_exhausted="unknown", **batch_kw)
            elif all(_tpu_eligible(model, es) for es in sub):
                rs = sup.run(model, sub, ladder=("tpu", "host"),
                             deadline=wd, budget=budget,
                             on_exhausted="unknown", **batch_kw)
            else:
                rs = sup.run(model, sub, ladder=("host",),
                             time_limit=lane_limit(), deadline=wd,
                             budget=budget, on_exhausted="unknown")
            for i, r in zip(rest, rs):
                out[i] = r
        return out

    @staticmethod
    def _render_invalid(test, history, d, opts) -> None:
        """On an invalid verdict, write linear.svg of the failed window
        into the test's store dir (checker.clj:130-137)."""
        if d.get("valid") is not False:
            return
        from .perf import out_path
        from . import linear_report

        path = out_path(test or {}, opts, "linear.svg")
        if path is None:
            return
        try:
            written = linear_report.render_analysis(history, d, path)
            if written:
                d["counterexample_svg"] = written
        except Exception:  # noqa: BLE001 — rendering must not mask verdicts
            import logging

            logging.getLogger("jepsen_tpu.checker.linearizable").warning(
                "linear.svg rendering failed", exc_info=True)

    def _competition(self, model, es) -> dict:
        """Race two genuinely different algorithms — just-in-time
        linearization vs the WGL search (on TPU when the model has a
        kernel encoding, host otherwise); first definite (non-unknown)
        verdict wins (knossos.competition parity, checker.clj:125-127).
        A pathological history that defeats one search order still gets
        a verdict from the other."""
        entrants: list = [
            (
                "linear",
                lambda: linear_mod.analysis(
                    model, es, time_limit=self.time_limit
                ),
            )
        ]
        if _tpu_eligible(model, es):

            def tpu():
                from ..ops import wgl_tpu

                return wgl_tpu.analysis(model, es, time_limit=self.time_limit)

            entrants.append(("wgl-tpu", tpu))
        else:
            # prefer the native C++ engine over the pure-Python search
            # when the model has a kernel encoding (same algorithm,
            # GIL-free, ~16x the steps/sec)
            if _native_available(model, es):
                from ..ops import wgl_native

                entrants.append(
                    ("wgl-native",
                     lambda: wgl_native.analysis(
                         model, es, time_limit=self.time_limit)))
            else:
                entrants.append(
                    (
                        "wgl-host",
                        lambda: wgl_host.analysis(
                            model, es, time_limit=self.time_limit
                        ),
                    )
                )

        n_entrants = len(entrants)
        done = threading.Event()
        results: dict = {}
        lock = threading.Lock()

        def run(name, fn):
            try:
                r = fn()
            except Exception as e:  # noqa: BLE001
                r = wgl_host.WGLResult(valid="unknown")
                r.error = str(e)  # type: ignore[attr-defined]
            with lock:
                results[name] = r
                if r.valid != "unknown" or len(results) == n_entrants:
                    done.set()

        threads = [
            threading.Thread(target=run, args=(name, fn), daemon=True)
            for name, fn in entrants
        ]
        for t in threads:
            t.start()
        done.wait()
        for t in threads:
            if t.is_alive():
                _abandoned_racers.append(t)
        with lock:
            for r in results.values():
                if r.valid != "unknown":
                    return self._result(r)
            return self._result(next(iter(results.values())))

    def _result(self, r) -> dict:
        d: dict[str, Any] = {"valid": r.valid}
        if r.valid is False:
            if r.op is not None:
                d["op"] = r.op.to_dict()
            if r.best_linearization is not None:
                d["final_paths"] = [
                    [o.to_dict() for o in r.best_linearization[:TRUNCATE]]
                ]
        # knossos.linear results carry :configs (checker.clj:138-141)
        configs = getattr(r, "configs", None)
        if configs:
            d["configs"] = configs[:TRUNCATE]
        if r.valid == "unknown" and getattr(r, "error", None):
            # why the verdict degraded ("deadline" from a budget-
            # expired chunk, a competition loser's exception text)
            d["error"] = r.error
        d["cache_size"] = r.cache_size
        d["steps"] = r.steps
        return d


def linearizable(model=None, algorithm="auto", time_limit=None) -> Linearizable:
    return Linearizable(model, algorithm, time_limit)
