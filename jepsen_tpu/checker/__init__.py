"""Checker protocol and combinators (reference: jepsen.checker,
checker.clj:49-108).

A checker validates a recorded history. `check(test, history, opts)`
returns a dict with at least {"valid": True | False | "unknown"}.
Exceptions become {"valid": "unknown", "error": ...} via check_safe;
compose() runs a map of checkers (in parallel threads) and merges their
validities with false > unknown > true dominance (checker.clj:26-47).

test is the test map (jepsen's immutable test map, core.clj:540-560);
opts may carry {"subdirectory": ...} for file-writing checkers.

Checker registry
----------------
`REGISTRY` maps the names the CLI's --checker flag accepts to zero-arg
factories, resolved uniformly by `resolve(name)`:

  linearizable   single-register linearizability via the supervised
                 WGL engine ladder (checker/linearizable.py)
  cycle          Elle-style transactional cycle checker — dependency
                 inference + Adya G0/G1c/G-single/G2 classification
                 via matrix closure on the closure-engine ladder
                 (checker/cycle/)
  timeline       render the history as an HTML timeline
  clock          clock-skew plot
  perf           latency/rate graphs
  recovery       nemesis fault/recovery audit
  unbridled-optimism  everything is awesome (a no-op baseline)

Workload-specific checkers (bank's SI total, long_fork's fork finder,
adya's G2 counter) come from their workload bundles; the transactional
three route through `cycle` internally.
"""

from __future__ import annotations

import threading
import traceback
from typing import Any, Mapping

from ..util import bounded_pmap

VALID_PRIORITIES = {True: 0, "unknown": 0.5, False: 1}


def merge_valid(valids) -> Any:
    """The highest-priority validity: any False wins, else any "unknown",
    else True (checker.clj:33-47)."""
    out = True
    for v in valids:
        if v not in VALID_PRIORITIES:
            raise ValueError(f"{v!r} is not a known valid value")
        if VALID_PRIORITIES[v] > VALID_PRIORITIES[out]:
            out = v
    return out


class Checker:
    def check(self, test: Mapping, history, opts: Mapping | None = None) -> dict:
        raise NotImplementedError


def check_safe(checker: Checker, test, history, opts=None) -> dict:
    """check(), but exceptions are wrapped as unknown verdicts
    (checker.clj:66-77)."""
    try:
        return checker.check(test, history, opts or {})
    except Exception:  # noqa: BLE001
        return {"valid": "unknown", "error": traceback.format_exc()}


class Compose(Checker):
    """Runs a name->checker map in parallel; result maps each name to its
    sub-result plus a merged top-level "valid" (checker.clj:79-91)."""

    def __init__(self, checker_map: Mapping[str, Checker]):
        self.checker_map = dict(checker_map)

    def check(self, test, history, opts=None) -> dict:
        items = list(self.checker_map.items())
        results = bounded_pmap(
            lambda kv: (kv[0], check_safe(kv[1], test, history, opts)), items
        )
        out = dict(results)
        out["valid"] = merge_valid(r["valid"] for _, r in results)
        return out


def compose(checker_map) -> Compose:
    return Compose(checker_map)


class ConcurrencyLimit(Checker):
    """Bounds concurrent executions of a memory-hungry checker with a
    semaphore (checker.clj:93-108)."""

    def __init__(self, limit: int, checker: Checker):
        self.sem = threading.Semaphore(limit)
        self.checker = checker

    def check(self, test, history, opts=None) -> dict:
        with self.sem:
            return self.checker.check(test, history, opts)


def concurrency_limit(limit: int, checker: Checker) -> ConcurrencyLimit:
    return ConcurrencyLimit(limit, checker)


class UnbridledOptimism(Checker):
    """Everything is awesoooommmmme! (checker.clj:110-114)"""

    def check(self, test, history, opts=None) -> dict:
        return {"valid": True}


def unbridled_optimism() -> UnbridledOptimism:
    return UnbridledOptimism()


# Re-exports of the concrete checkers
from .basic import (  # noqa: E402
    counter,
    queue,
    set_checker,
    set_full,
    total_queue,
    unique_ids,
)
from .linearizable import linearizable  # noqa: E402
from .clock import clock_plot  # noqa: E402
from .timeline import html as timeline_html  # noqa: E402
# NB: the composite perf checker is exported as perf_checker — the bare
# name `perf` is taken by the jepsen_tpu.checker.perf submodule, and a
# same-named function would be clobbered by any submodule import.
from .perf import (  # noqa: E402
    latency_graph,
    perf as perf_checker,
    rate_graph_checker as rate_graph,
)
from .recovery import RecoveryChecker, recovery  # noqa: E402
# the cycle subsystem imports Checker from this package, so it loads
# after the base protocol is defined (same pattern as the re-exports)
from . import cycle  # noqa: E402

# --checker names -> zero-arg checker factories (see module docstring)
REGISTRY = {
    "linearizable": linearizable,
    "cycle": cycle.checker,
    "timeline": timeline_html,
    "clock": clock_plot,
    "perf": perf_checker,
    "recovery": recovery,
    "unbridled-optimism": unbridled_optimism,
}


def resolve(name: str) -> Checker:
    """Instantiate a registered checker by CLI name."""
    try:
        factory = REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown checker {name!r}; known: {sorted(REGISTRY)}"
        ) from None
    return factory()


__all__ = [
    "Checker",
    "REGISTRY",
    "RecoveryChecker",
    "check_safe",
    "clock_plot",
    "compose",
    "concurrency_limit",
    "counter",
    "cycle",
    "latency_graph",
    "linearizable",
    "merge_valid",
    "perf_checker",
    "queue",
    "rate_graph",
    "recovery",
    "resolve",
    "set_checker",
    "set_full",
    "timeline_html",
    "total_queue",
    "unbridled_optimism",
    "unique_ids",
]
