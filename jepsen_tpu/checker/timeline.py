"""HTML timeline of a history's concurrency windows (reference:
jepsen.checker.timeline, checker/timeline.clj).

Each process gets a column; each operation is a box spanning its
invoke..completion window, colored by outcome, with full op details in
the hover title (timeline.clj:97-121). Writes timeline.html into the
test's store dir.
"""

from __future__ import annotations

import html as html_mod
import logging
from typing import Mapping

from ..util import nanos_to_ms
from . import Checker

log = logging.getLogger("jepsen_tpu.checker.timeline")

#: ns per pixel (timeline.clj:20)
TIMESCALE = 1e6
COL_WIDTH = 100
GUTTER = 106
HEIGHT = 16

STYLESHEET = """
.ops        { position: absolute; }
.op         { position: absolute; padding: 2px; border-radius: 2px;
              box-shadow: 0 1px 3px rgba(0,0,0,0.12),
                          0 1px 2px rgba(0,0,0,0.24);
              overflow: hidden; font-size: 11px;
              font-family: sans-serif; }
.op.invoke  { background: #eeeeee; }
.op.ok      { background: #6DB6FE; }
.op.info    { background: #FFAA26; }
.op.fail    { background: #FEB5DA; }
.op:target  { box-shadow: 0 14px 28px rgba(0,0,0,0.25),
                          0 10px 10px rgba(0,0,0,0.22); }
"""


def op_pairs(history):
    """[invoke, completion|None] windows plus unmatched [info] singletons,
    in history order (timeline.clj:33-53)."""
    pending: dict = {}
    out = []
    for o in history:
        if o.is_invoke:
            assert o.process not in pending, f"double invoke by {o.process}"
            rec = [o, None]
            pending[o.process] = rec
            out.append(rec)
        elif o.is_info and o.process not in pending:
            out.append([o, None])  # unmatched info (nemesis etc.)
        else:
            rec = pending.pop(o.process, None)
            if rec is not None:
                rec[1] = o
    return out


def _title(start, stop) -> str:
    lines = []
    if stop is not None:
        lines.append(f"Dur: {int(nanos_to_ms(stop.time - start.time))} ms")
        if stop.error is not None:
            lines.append(f"Err: {stop.error!r}")
    lines.append(f"Op: {start.to_dict()!r}")
    if stop is not None:
        lines.append(f"Completion: {stop.to_dict()!r}")
    return "\n".join(lines)


def _process_index(history) -> dict:
    idx: dict = {}
    for o in history:
        if o.process not in idx:
            idx[o.process] = len(idx)
    return idx


#: witness-arrow stroke per dependency relation (checker/cycle)
REL_COLORS = {"ww": "#C62828", "wr": "#1565C0", "rw": "#EF6C00",
              "realtime": "#555555"}


def _witness_svg(witness, pos, width, height) -> str:
    """An absolutely-positioned SVG overlay drawing each witness-cycle
    edge as an op -> op arrow labeled with its relation. `witness` is
    a list of cycle-checker witness dicts ({"steps": [{"from": index,
    "to": index, "rel": ...}]}); `pos` maps op index -> box center."""
    lines = []
    for w in witness or []:
        for s in w.get("steps", []):
            a, b = pos.get(s.get("from")), pos.get(s.get("to"))
            if a is None or b is None:
                continue
            rel = str(s.get("rel", "?"))
            color = REL_COLORS.get(rel, "#000000")
            (x1, y1), (x2, y2) = a, b
            mx, my = (x1 + x2) / 2, (y1 + y2) / 2
            lines.append(
                f'<line x1="{x1:.0f}" y1="{y1:.1f}" x2="{x2:.0f}" '
                f'y2="{y2:.1f}" stroke="{color}" stroke-width="2" '
                f'marker-end="url(#arrow)"/>'
                f'<text x="{mx:.0f}" y="{my:.1f}" fill="{color}" '
                f'font-size="11" font-family="sans-serif">'
                f"{html_mod.escape(rel)}</text>"
            )
    if not lines:
        return ""
    return (
        f'<svg class="witness" width="{width:.0f}" '
        f'height="{height:.0f}" style="position:absolute;left:0;top:0;'
        f'pointer-events:none">'
        '<defs><marker id="arrow" viewBox="0 0 10 10" refX="9" refY="5" '
        'markerWidth="7" markerHeight="7" orient="auto-start-reverse">'
        '<path d="M 0 0 L 10 5 L 0 10 z" fill="context-stroke"/>'
        "</marker></defs>" + "".join(lines) + "</svg>"
    )


def render(test, history, end_time_nanos=None, witness=None) -> str:
    """The full HTML document (timeline.clj:123-157). `witness` takes
    cycle-checker witnesses (result["anomalies"] values flattened) and
    overlays their dependency edges as labeled arrows."""
    procs = _process_index(history)
    times = [o.time for o in history if o.time is not None and o.time >= 0]
    t_end = end_time_nanos if end_time_nanos is not None else (
        max(times) if times else 0
    )
    divs = []
    pos: dict = {}
    max_bottom = 0.0
    for start, stop in op_pairs(history):
        if start.time is None or start.time < 0:
            continue
        cls = stop.type if stop is not None else (
            "info" if start.is_info else "invoke"
        )
        left = GUTTER * procs[start.process]
        top = start.time / TIMESCALE
        bottom = (stop.time if stop is not None else t_end) / TIMESCALE
        height = max(HEIGHT, bottom - top)
        # either end of the op window addresses this box (cycle
        # witnesses carry completion indices)
        center = (left + COL_WIDTH / 2, top + height / 2)
        pos[start.index] = center
        if stop is not None:
            pos.setdefault(stop.index, center)
        max_bottom = max(max_bottom, top + height)
        label = f"{start.process} {start.f} {start.value!r}"
        divs.append(
            f'<div id="op-{start.index}" class="op {cls}" '
            f'style="left:{left:.0f}px;top:{top:.1f}px;'
            f'width:{COL_WIDTH}px;height:{height:.1f}px" '
            f'title="{html_mod.escape(_title(start, stop), quote=True)}">'
            f"{html_mod.escape(label)}</div>"
        )
    svg = _witness_svg(witness, pos, GUTTER * max(len(procs), 1),
                       max_bottom + HEIGHT)
    name = html_mod.escape(str(test.get("name", "test")))
    return (
        "<!doctype html><html><head>"
        f"<title>{name} timeline</title>"
        f"<style>{STYLESHEET}</style></head><body>"
        f"<h1>{name}</h1>"
        f'<div class="ops">{"".join(divs)}{svg}</div>'
        "</body></html>"
    )


class HtmlTimeline(Checker):
    """Writes timeline.html (timeline.clj:159-179). opts["witness"]
    (cycle-checker witnesses) overlays dependency-cycle arrows."""

    def check(self, test: Mapping, history, opts=None) -> dict:
        doc = render(test, history, witness=(opts or {}).get("witness"))
        if test.get("name") and test.get("start_time"):
            from .. import store

            p = store.path_(
                test, list((opts or {}).get("subdirectory") or []),
                "timeline.html",
            )
            with open(p, "w") as f:
                f.write(doc)
        return {"valid": True}


def html() -> HtmlTimeline:
    return HtmlTimeline()
