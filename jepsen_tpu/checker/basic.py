"""Non-search checkers: linear scans and reductions over the history.

Parity targets (result-map keys and verdict logic) are the reference's
jepsen.checker implementations — file:line cites on each class. These are
the O(n) checkers; the NP-hard linearizability search lives in
checker/linearizable.py.
"""

from __future__ import annotations

from collections import Counter
from dataclasses import dataclass, field
from typing import Any

from ..history import Op, complete, op as to_op
from ..models import inconsistent
from ..util import integer_interval_set_str, nanos_to_ms
from . import Checker


def _ops(history):
    return [to_op(o) for o in history]


class Queue(Checker):
    """Every dequeue must come from somewhere: assume every non-failing
    enqueue succeeded and only ok dequeues succeeded, then fold the model
    over that sequence (checker.clj:143-163). Use with an unordered-queue
    model; O(n)."""

    def __init__(self, model):
        self.model = model

    def check(self, test, history, opts=None) -> dict:
        state = self.model
        for o in _ops(history):
            take = (o.f == "enqueue" and o.is_invoke) or (
                o.f == "dequeue" and o.is_ok
            )
            if not take:
                continue
            state = state.step(o.f, o.value)
            if inconsistent(state):
                return {"valid": False, "error": state.msg}
        return {"valid": True, "final_queue": state}


def queue(model) -> Queue:
    return Queue(model)


class SetChecker(Checker):
    """:add operations followed by a final :read of the whole set
    (checker.clj:165-216). Verifies every acknowledged add is present and
    nothing unexpected appears."""

    def check(self, test, history, opts=None) -> dict:
        ops = _ops(history)
        attempts = {o.value for o in ops if o.is_invoke and o.f == "add"}
        adds = {o.value for o in ops if o.is_ok and o.f == "add"}
        final_read = None
        for o in ops:
            if o.is_ok and o.f == "read":
                final_read = o.value
        if final_read is None:
            return {"valid": "unknown", "error": "Set was never read"}
        final_read = set(final_read)
        ok = final_read & attempts
        unexpected = final_read - attempts
        lost = adds - final_read
        recovered = ok - adds
        return {
            "valid": not lost and not unexpected,
            "attempt_count": len(attempts),
            "acknowledged_count": len(adds),
            "ok_count": len(ok),
            "lost_count": len(lost),
            "recovered_count": len(recovered),
            "unexpected_count": len(unexpected),
            "ok": integer_interval_set_str(ok),
            "lost": integer_interval_set_str(lost),
            "unexpected": integer_interval_set_str(unexpected),
            "recovered": integer_interval_set_str(recovered),
        }


def set_checker() -> SetChecker:
    return SetChecker()


@dataclass
class _SetElement:
    """Per-element timeline state for set-full (checker.clj:238-263):
    known = op confirming existence (add ok or first observing read);
    last_present / last_absent = most recent read *invocations* that did /
    didn't observe the element."""

    element: Any
    known: Op | None = None
    last_present: Op | None = None
    last_absent: Op | None = None

    def add_ok(self, o: Op):
        if self.known is None:
            self.known = o

    def read_present(self, inv: Op, o: Op):
        if self.known is None:
            self.known = o
        if self.last_present is None or self.last_present.index < inv.index:
            self.last_present = inv

    def read_absent(self, inv: Op, o: Op):
        if self.last_absent is None or self.last_absent.index < inv.index:
            self.last_absent = inv

    def results(self) -> dict:
        """Final per-element outcome (checker.clj:265-330). An element is
        stable if some read invoked after the last absent read observed it;
        lost if it was known and the last absent read began after both the
        last present read and the known time; else never-read."""
        lp = self.last_present.index if self.last_present else -1
        la = self.last_absent.index if self.last_absent else -1
        stable = self.last_present is not None and la < lp
        lost = (
            self.known is not None
            and self.last_absent is not None
            and lp < la
            and self.known.index < la
        )
        stable_time = (
            (self.last_absent.time + 1 if self.last_absent else 0)
            if stable
            else None
        )
        lost_time = (
            (self.last_present.time + 1 if self.last_present else 0)
            if lost
            else None
        )
        known_time = self.known.time if self.known else 0
        return {
            "element": self.element,
            "outcome": "stable" if stable else "lost" if lost else "never-read",
            "stable_latency": (
                int(nanos_to_ms(max(0, stable_time - known_time)))
                if stable
                else None
            ),
            "lost_latency": (
                int(nanos_to_ms(max(0, lost_time - known_time)))
                if lost
                else None
            ),
        }


def _frequency_distribution(points, coll):
    """Percentile map over a collection (checker.clj:332-343)."""
    xs = sorted(coll)
    if not xs:
        return None
    n = len(xs)
    return {p: xs[min(n - 1, int(n * p))] for p in points}


class SetFull(Checker):
    """Rigorous set analysis over a full timeline of adds and
    whole-set reads (checker.clj:345-503): classifies each element as
    stable / lost / never-read, computes stable & lost latencies, flags
    stale (slow-to-appear) elements, and — with linearizable=True — fails
    on staleness too."""

    def __init__(self, linearizable: bool = False):
        self.linearizable = linearizable

    def check(self, test, history, opts=None) -> dict:
        elements: dict = {}
        reads: dict = {}  # process -> read invocation
        for o in _ops(history):
            if not isinstance(o.process, int):
                continue  # ignore the nemesis
            if o.f == "add":
                if o.is_invoke:
                    elements.setdefault(o.value, _SetElement(o.value))
                elif o.is_ok:
                    e = elements.get(o.value)
                    if e is not None:
                        e.add_ok(o)
            elif o.f == "read":
                if o.is_invoke:
                    reads[o.process] = o
                elif o.is_fail:
                    reads.pop(o.process, None)
                elif o.is_ok:
                    inv = reads.pop(o.process, o)
                    v = set(o.value)
                    for element, state in elements.items():
                        if element in v:
                            state.read_present(inv, o)
                        else:
                            state.read_absent(inv, o)
        rs = [e.results() for e in elements.values()]
        outcomes: dict = {}
        for r in rs:
            outcomes.setdefault(r["outcome"], []).append(r)
        stable = outcomes.get("stable", [])
        lost = outcomes.get("lost", [])
        never_read = outcomes.get("never-read", [])
        stale = [r for r in stable if r["stable_latency"] > 0]
        worst_stale = sorted(
            stale, key=lambda r: r["stable_latency"], reverse=True
        )[:8]
        if lost:
            valid: Any = False
        elif not stable:
            valid = "unknown"
        elif self.linearizable and stale:
            valid = False
        else:
            valid = True
        out = {
            "valid": valid,
            "attempt_count": len(rs),
            "stable_count": len(stable),
            "lost_count": len(lost),
            "lost": sorted(r["element"] for r in lost),
            "never_read_count": len(never_read),
            "never_read": sorted(r["element"] for r in never_read),
            "stale_count": len(stale),
            "stale": sorted(r["element"] for r in stale),
            "worst_stale": worst_stale,
        }
        points = (0, 0.5, 0.95, 0.99, 1)
        sl = _frequency_distribution(
            points, [r["stable_latency"] for r in rs if r["stable_latency"] is not None]
        )
        ll = _frequency_distribution(
            points, [r["lost_latency"] for r in rs if r["lost_latency"] is not None]
        )
        if sl:
            out["stable_latencies"] = sl
        if ll:
            out["lost_latencies"] = ll
        return out


def set_full(linearizable: bool = False) -> SetFull:
    return SetFull(linearizable)


def expand_queue_drain_ops(history) -> list:
    """Expand :drain ops (value = collection of elements) into dequeue
    invoke/ok pairs (checker.clj:505-537).

    A crashed (:info) drain that carries a partial element list — e.g.
    disque's drain hitting its deadline after acking some jobs — has
    those elements expanded too (they were definitely consumed); the
    drain's incompleteness is preserved simply by not having drained
    the rest. Only a crashed drain with NO value is unhandleable, as in
    the reference."""
    out = []
    for o in _ops(history):
        if o.f != "drain":
            out.append(o)
        elif o.is_invoke or o.is_fail:
            continue
        elif o.is_ok or (o.is_info and isinstance(o.value, (list, tuple))):
            for element in o.value:
                out.append(o.with_(type="invoke", f="dequeue", value=None))
                out.append(o.with_(type="ok", f="dequeue", value=element))
        else:
            raise ValueError(f"can't handle a crashed drain operation: {o}")
    return out


class TotalQueue(Checker):
    """What goes in must come out — multiset analysis of enqueues vs
    dequeues; requires the history to drain the queue
    (checker.clj:539-598)."""

    def check(self, test, history, opts=None) -> dict:
        ops = expand_queue_drain_ops(history)
        attempts = Counter(
            o.value for o in ops if o.is_invoke and o.f == "enqueue"
        )
        enqueues = Counter(o.value for o in ops if o.is_ok and o.f == "enqueue")
        dequeues = Counter(o.value for o in ops if o.is_ok and o.f == "dequeue")
        ok = dequeues & attempts
        unexpected = Counter(
            {v: n for v, n in dequeues.items() if v not in attempts}
        )
        duplicated = dequeues - attempts - unexpected
        lost = enqueues - dequeues
        recovered = ok - enqueues
        return {
            "valid": not lost and not unexpected,
            "attempt_count": sum(attempts.values()),
            "acknowledged_count": sum(enqueues.values()),
            "ok_count": sum(ok.values()),
            "unexpected_count": sum(unexpected.values()),
            "duplicated_count": sum(duplicated.values()),
            "lost_count": sum(lost.values()),
            "recovered_count": sum(recovered.values()),
            "lost": dict(lost),
            "unexpected": dict(unexpected),
            "duplicated": dict(duplicated),
            "recovered": dict(recovered),
        }


def total_queue() -> TotalQueue:
    return TotalQueue()


class UniqueIds(Checker):
    """A unique-id generator must actually emit unique ids: :generate
    invokes answered by :ok with distinct values (checker.clj:600-645)."""

    def check(self, test, history, opts=None) -> dict:
        ops = _ops(history)
        attempted = sum(1 for o in ops if o.is_invoke and o.f == "generate")
        acks = [o.value for o in ops if o.is_ok and o.f == "generate"]
        counts = Counter(acks)
        dups = {k: n for k, n in counts.items() if n > 1}
        rng = [min(acks), max(acks)] if acks else None
        worst = dict(
            sorted(dups.items(), key=lambda kv: kv[1], reverse=True)[:48]
        )
        return {
            "valid": not dups,
            "attempted_count": attempted,
            "acknowledged_count": len(acks),
            "duplicated_count": len(dups),
            "duplicated": worst,
            "range": rng,
        }


def unique_ids() -> UniqueIds:
    return UniqueIds()


class CounterChecker(Checker):
    """A monotonically-increasing counter: each read must fall between the
    sum of acknowledged increments (lower bound at its invocation) and the
    sum of attempted increments (upper bound at its completion)
    (checker.clj:648-701)."""

    def check(self, test, history, opts=None) -> dict:
        lower = 0
        upper = 0
        pending: dict = {}  # process -> (lower-at-invoke, value)
        reads = []
        for o in complete(_ops(history)):
            key = (o.type, o.f)
            if key == ("invoke", "read"):
                pending[o.process] = (lower, o.value)
            elif key == ("ok", "read"):
                lo, v = pending.pop(o.process, (lower, o.value))
                reads.append((lo, v, upper))
            elif key == ("invoke", "add"):
                upper += o.value
            elif key == ("ok", "add"):
                lower += o.value
        errors = [r for r in reads if not (r[0] <= r[1] <= r[2])]
        return {"valid": not errors, "reads": reads, "errors": errors}


def counter() -> CounterChecker:
    return CounterChecker()
