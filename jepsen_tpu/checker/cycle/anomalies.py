"""Adya anomaly classification over a dependency graph.

Given the relation matrices from deps.extract, each anomaly is a cycle
shape, detected by masking WHICH relations may participate (Adya's
taxonomy, via Elle):

  G0        cycle of ww edges only (write cycle)
  G1c       cycle of ww|wr edges with at least one wr (circular
            information flow)
  G-single  cycle with exactly one rw edge (read skew / SI's
            characteristic anomaly)
  G2        cycle with two or more rw edges (anti-dependency cycle)

Detection reduces to transitive closure: an edge a -r-> b lies on a
qualifying cycle iff b reaches a through the allowed mask —

  G0 hits        ww  & closure(ww).T
  G1c hits       wr  & closure(ww|wr).T
  G-single hits  rw  & closure(ww|wr).T      (the return path has no
                                              rw, so the cycle has
                                              exactly one)
  G2 hits        rw  & closure(ww|wr|rw).T   minus G-single hits

With realtime in play (strict-serializability checking), the realtime
relation is simply OR-ed into every mask.

The closure itself is the expensive step, and it runs behind the
closure-engine supervisor (checker/supervisor.py get_closure): the
graph is first split into weakly-connected components — cycles cannot
cross components, and per-key sharding (independent.py) makes many
small components the common case (P-compositionality) — and every
component x mask matrix goes to the device in ONE supervised batch,
so watchdogs, circuit breakers, and TPU->host demotion apply
unchanged. Witness recovery (a concrete shortest cycle per anomaly,
for the report and the timeline) is host BFS on the tiny flagged
component.
"""

from __future__ import annotations

import numpy as np

from ...ops import closure_host
from .deps import DepGraph

ANOMALIES = ("G0", "G1c", "G-single", "G2")

# anomaly -> (relations allowed in the cycle, relation the hit edge
# must carry)
_MASKS = {
    "G0": (("ww",), "ww"),
    "G1c": (("ww", "wr"), "wr"),
    "G-single": (("ww", "wr"), "rw"),
    "G2": (("ww", "wr", "rw"), "rw"),
}


def components(full: np.ndarray) -> list:
    """Weakly-connected components of the union graph, as index
    arrays; singletons without a self-loop are dropped (no cycle can
    involve them)."""
    n = full.shape[0]
    und = full | full.T
    label = np.full(n, -1, dtype=np.int64)
    comps: list = []
    for s in range(n):
        if label[s] >= 0:
            continue
        stack = [s]
        label[s] = len(comps)
        members = [s]
        while stack:
            u = stack.pop()
            for v in np.flatnonzero(und[u]):
                if label[v] < 0:
                    label[v] = len(comps)
                    members.append(int(v))
                    stack.append(int(v))
        comps.append(np.array(sorted(members), dtype=np.int64))
    return [c for c in comps
            if len(c) > 1 or full[c[0], c[0]]]


def _job_key(rels, sub: np.ndarray) -> str:
    """Content identity of one closure job (relation mask + the exact
    component submatrix), so journaled closures are only reused for
    bit-identical inputs."""
    import hashlib

    h = hashlib.sha1()
    h.update(("|".join(rels) + f"#{sub.shape[0]}#").encode())
    h.update(np.packbits(sub).tobytes())
    return h.hexdigest()


def _pack_closure(m: np.ndarray) -> dict:
    return {"n": int(m.shape[0]),
            "bits": np.packbits(m).tobytes().hex()}


def _unpack_closure(d) -> np.ndarray:
    n = int(d["n"])
    bits = np.frombuffer(bytes.fromhex(d["bits"]), dtype=np.uint8)
    return np.unpackbits(bits, count=n * n).astype(bool).reshape(n, n)


def _closures(mats, engine=None, budget=None) -> list:
    """Closure of every matrix, through the supervised ladder by
    default or a pinned engine ("host" / "tpu" / "mesh") for parity
    tooling. ``budget`` (absolute time.monotonic deadline) rides the
    supervised path only — pinned engines are parity tools and run to
    completion."""
    if not mats:
        return []
    if engine == "host":
        return closure_host.reach_batch(mats)
    if engine == "tpu":
        from ...ops import closure_tpu

        return closure_tpu.reach_batch(mats)
    if engine == "mesh":
        from ...ops import closure_tpu

        return closure_tpu.reach_batch_mesh(mats)
    from .. import supervisor as sup_mod

    sup = sup_mod.get_closure()
    # expired lanes resolve to None (an under-approximate closure
    # would silently hide anomalies); callers treat None as
    # deadline-expired and degrade that trace to unknown
    return sup.run(None, mats, ladder=sup_mod.CLOSURE_LADDER,
                   budget=budget, on_exhausted="raise",
                   expired_fill=lambda: None)


def _witness(g: DepGraph, comp, allowed, a, b) -> dict:
    """A concrete cycle through edge a -> b: the edge plus the
    shortest b -> a path inside the allowed-mask subgraph of one
    component (host BFS). Returns op indices + relation labels, the
    shape checker/timeline.py renders."""
    sub = allowed[np.ix_(comp, comp)]
    la = int(np.searchsorted(comp, a))
    lb = int(np.searchsorted(comp, b))
    path = closure_host.shortest_cycle_path(sub, lb, la)
    if path is None:  # can't happen if the closure was sound; degrade
        path = [lb, la]
    nodes = [a] + [int(comp[i]) for i in path]
    steps = []
    for u, v in zip(nodes, nodes[1:]):
        rels = g.rels_of(u, v)
        steps.append({
            "from": int(g.ops[u].index),
            "to": int(g.ops[v].index),
            "rel": rels[0] if rels else "?",
        })
    return {
        "cycle": [int(g.ops[i].index) for i in nodes],
        "steps": steps,
        "ops": [g.ops[i] for i in nodes[:-1]],
    }


def classify(g: DepGraph, anomalies=ANOMALIES, *, realtime=False,
             engine=None, max_witnesses=4, journal=None,
             budget=None) -> dict:
    """Find every requested anomaly in a dependency graph.

    Returns {"anomaly-types": [...], "anomalies": {type: [witness]},
    "cycle-count": int, "node-count": int, "component-count": int}.
    Witness lists are capped at max_witnesses per type; the hit COUNT
    (cycle-count) is exact.

    journal (a store.AnalysisJournal) makes the closure step
    resumable: each component x mask job is keyed by content hash, a
    journaled closure is reused (counted in the closure supervisor's
    journal_skips telemetry) and only the remaining jobs go to the
    engine; completed closures journal as packed bitmaps.

    budget (absolute time.monotonic deadline) bounds the closure
    step's wall clock; expiry raises EngineFailure(kind="deadline") —
    closures that DID complete are journaled first, so a retry with a
    fresh budget only computes the remainder."""
    for a in anomalies:
        if a not in _MASKS:
            raise ValueError(f"unknown anomaly {a!r} "
                             f"(known: {ANOMALIES})")
    anomalies = [a for a in ANOMALIES if a in anomalies]
    n = len(g)
    base = ("realtime",) if realtime and "realtime" in g.adj else ()
    # every distinct relation mask we need a closure of
    masks = {}
    for a in anomalies:
        rels = tuple(_MASKS[a][0]) + base
        masks.setdefault(rels, g.union(rels))
    full = g.union(("ww", "wr", "rw") + base)
    comps = components(full)
    # one supervised batch: |components| x |distinct masks| closures
    keys = list(masks)
    jobs = [(rels, c) for rels in keys for c in comps]
    mats = [masks[rels][np.ix_(c, c)] for rels, c in jobs]
    closed: list = [None] * len(jobs)
    jkeys: list = [None] * len(jobs)
    if journal is not None:
        for i, ((rels, _), m) in enumerate(zip(jobs, mats)):
            jkeys[i] = _job_key(rels, m)
            r = journal.get("closure", jkeys[i])
            if r is not None:
                try:
                    closed[i] = _unpack_closure(r)
                except (KeyError, TypeError, ValueError):
                    closed[i] = None
        skips = sum(1 for x in closed if x is not None)
        if skips:
            from .. import supervisor as sup_mod

            sup_mod.get_closure().telemetry.record("journal_skips",
                                                   skips)
    todo = [i for i, x in enumerate(closed) if x is None]
    # Component dealing: submit the batch LARGEST-first. Supervision
    # chunks split the list in submission order, so descending size
    # groups same-pad-bucket components into the same launches, and
    # the mesh rung's eligibility (which keys on the biggest matrix
    # in a chunk) sees the pod-scale components up front instead of
    # buried behind a run of singletons. Results realign by index.
    todo.sort(key=lambda i: -mats[i].shape[0])
    for i, sub in zip(todo, _closures([mats[i] for i in todo],
                                      engine=engine, budget=budget)):
        closed[i] = sub
        if sub is not None and journal is not None:
            journal.record("closure", jkeys[i], _pack_closure(sub))
    if any(x is None for x in closed):
        from .. import supervisor as sup_mod

        raise sup_mod.EngineFailure(
            "closure", "deadline",
            "closure budget expired before every component closed")
    # reassemble per-mask full-size closure (block-diagonal by
    # construction: no path leaves a weak component)
    closure = {rels: np.zeros((n, n), dtype=bool) for rels in keys}
    for (rels, c), sub in zip(jobs, closed):
        closure[rels][np.ix_(c, c)] = sub
    found: dict = {}
    types: list = []
    cycles = 0
    claimed = np.zeros((n, n), dtype=bool)  # G-single hits, for G2 dedup
    for a in anomalies:
        rels, hit_rel = _MASKS[a]
        allowed = masks[tuple(rels) + base]
        cl = closure[tuple(rels) + base]
        hits = g.adj[hit_rel] & cl.T
        if a == "G-single":
            claimed |= hits
        elif a == "G2":
            # when G-single also ran, its hits are the exactly-one-rw
            # cycles; without it, G2 keeps Adya's broad sense (>= 1 rw)
            hits = hits & ~claimed
        k = int(hits.sum())
        if not k:
            continue
        cycles += k
        types.append(a)
        ws = []
        ii, jj = np.nonzero(hits)
        for x, y in list(zip(ii, jj))[:max_witnesses]:
            x, y = int(x), int(y)
            comp = next(c for c in comps if x in c)
            # the return path b -> a stays inside the allowed mask (the
            # closure proved it exists there); the hit edge itself is
            # prepended from the real adjacency
            w = _witness(g, comp, allowed, x, y)
            w["type"] = a
            ws.append(w)
        found[a] = ws
    return {
        "anomaly-types": types,
        "anomalies": found,
        "cycle-count": cycles,
        "node-count": n,
        "component-count": len(comps),
    }
