"""Dependency-graph inference from transactional histories (Elle).

Reference: elle.core / elle.list-append / elle.rw-register — infer,
from the observed values alone, which transactions must have depended
on which, and emit the result as dense boolean adjacency matrices the
closure engines (ops/closure_tpu.py / ops/closure_host.py) consume.

Nodes are ok transactions (one node per completed op). Relations:

  ww  write-write: T1 installed a version that T2 overwrote/extended
  wr  write-read:  T2 read the version T1 installed
  rw  read-write (anti-dependency): T1 read a version that T2 replaced
  realtime  T1's completion preceded T2's invocation (optional — only
            computed when asked for; it is dense, O(n^2) edges)

Two inference modes, chosen PER KEY by the micro-ops touching it:

* list-append (txn.APPEND mops): reads return the key's whole list, so
  the version order is recoverable exactly — it is the longest read
  list, and every other read must be a prefix of it (prefix
  consistency; violations raise IllegalInference, the history is
  uncheckable, not invalid). The writer of element i ww-precedes the
  writer of element i+1; the writer of a read's last element wr-feeds
  the reader; a reader of prefix v_1..v_i rw-precedes the writer of
  v_{i+1}; a reader of [] rw-precedes the writer of v_1. Appends never
  observed by any read get no position (and no edges) — Elle does the
  same; recoverability, not completeness, is the contract.

* rw-register (txn.WRITE/READ mops): versions are single values, so a
  version order needs an assumption, picked by `version_order`:
  "write-once" (each key written at most once — long_fork, adya) or
  "value" (writes ordered by value — the causal workload's counter
  writes 1, 2, ...). Reads of an unwritten key observe the initial
  version (None, plus anything in `init_values`).

Both modes require written values to be attributable: a value written
twice to one key, or a read of a value nobody wrote, raises
IllegalInference (checker surfaces it as valid="unknown").
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ... import txn as mop
from ...history import Op, pairs as _pairs

RELATIONS = ("ww", "wr", "rw")

_INIT = object()  # the pre-history version of a register key


class IllegalInference(Exception):
    """The history's reads don't determine a version order (non-prefix
    read, duplicate write, phantom value) — uncheckable, not invalid."""

    def __init__(self, msg, **info):
        super().__init__(msg)
        self.info = {"msg": msg, **info}


@dataclass
class DepGraph:
    """A dependency graph over the ok transactions of one history.

    ops[i] is node i's completion Op; adj maps each relation name to a
    dense [n, n] bool matrix (adj[r][i, j]: i r-precedes j)."""

    ops: list
    adj: dict = field(default_factory=dict)

    def __len__(self) -> int:
        return len(self.ops)

    def union(self, rels) -> np.ndarray:
        """OR of the named relations' matrices."""
        n = len(self.ops)
        out = np.zeros((n, n), dtype=bool)
        for r in rels:
            m = self.adj.get(r)
            if m is not None:
                out |= m
        return out

    def edges(self, rel) -> list:
        """[(i, j), ...] for one relation (diagnostics/tests)."""
        ii, jj = np.nonzero(self.adj[rel])
        return [(int(i), int(j)) for i, j in zip(ii, jj)]

    def rels_of(self, i: int, j: int) -> list:
        """Every relation containing edge i -> j, in RELATIONS order
        (+ realtime last) — used to label witness edges."""
        order = [r for r in (*RELATIONS, "realtime") if r in self.adj]
        return [r for r in order if self.adj[r][i, j]]


# ---------------------------------------------------------------------------
# History -> micro-op transactions

def txns_of(history, key=None) -> list:
    """[(op, micro-ops), ...] for every ok op carrying a micro-op txn
    value. Register-style ops (scalar value, f in read/read-init/write)
    are lifted to single-mop txns against `key` (the independent
    history_key, or 0) so register workloads need no adapter."""
    out = []
    k = key if key is not None else 0
    for o in history:
        if not o.is_ok:
            continue
        v = o.value
        if isinstance(v, (list, tuple)) and v and all(
                mop.is_op(m) for m in v):
            out.append((o, [list(m) for m in v]))
        elif isinstance(v, (dict, list, tuple, set)):
            # aggregate payloads (e.g. bank's {account: balance}
            # snapshots) carry no attributable versions — no node
            continue
        elif o.f in ("read", "read-init"):
            out.append((o, [[mop.READ, k, v]]))
        elif o.f == "write":
            out.append((o, [[mop.WRITE, k, v]]))
    return out


# ---------------------------------------------------------------------------
# Per-key version orders

def _append_key_edges(k, appends, reads, add):
    """List-append inference for one key (elle.list-append): version
    order = the longest read list, prefix-checked against every other
    read."""
    writer = {}
    for node, v in appends:
        if v in writer:
            raise IllegalInference(
                f"value {v!r} appended to key {k!r} more than once",
                key=k, value=v)
        writer[v] = node
    longest: list = []
    for node, obs in reads:
        obs = list(obs or [])
        if len(obs) > len(longest):
            longest = obs
    order = longest
    for node, obs in reads:
        obs = list(obs or [])
        if obs != order[:len(obs)]:
            raise IllegalInference(
                f"read of key {k!r} is not a prefix of the longest "
                f"read — no total version order exists",
                key=k, read=obs, longest=order)
    for v in order:
        if v not in writer:
            raise IllegalInference(
                f"read of key {k!r} observed {v!r}, which no txn "
                f"appended", key=k, value=v)
    # ww: consecutive observed versions
    for a, b in zip(order, order[1:]):
        add("ww", writer[a], writer[b])
    for node, obs in reads:
        obs = list(obs or [])
        # wr: the read observed exactly the state the last element's
        # appender installed
        if obs:
            add("wr", writer[obs[-1]], node)
        # rw: the read missed every later version; the next one's
        # appender overwrote what it saw
        if len(obs) < len(order):
            add("rw", node, writer[order[len(obs)]])


def _register_key_edges(k, writes, reads, add, *, version_order,
                        init_values):
    """rw-register inference for one key under the `version_order`
    assumption ("write-once" or "value")."""
    vals = [v for _, v in writes]
    if len(set(vals)) != len(vals):
        dup = next(v for v in vals if vals.count(v) > 1)
        raise IllegalInference(
            f"value {dup!r} written to key {k!r} more than once — "
            f"reads cannot be attributed", key=k, value=dup)
    if version_order == "write-once":
        if len(writes) > 1:
            raise IllegalInference(
                f"key {k!r} written {len(writes)} times under the "
                f"write-once order", key=k)
        ordered = list(writes)
    elif version_order == "value":
        ordered = sorted(writes, key=lambda nv: nv[1])
    else:
        raise ValueError(f"unknown version_order {version_order!r}")
    versions = [(_INIT, None)] + [(node, v) for node, v in ordered]
    pos = {v: i for i, (_, v) in enumerate(versions) if i > 0}
    for (w1, _), (w2, _) in zip(versions[1:], versions[2:]):
        add("ww", w1, w2)
    inits = {None, *init_values}
    for node, v in reads:
        if v in inits and v not in pos:
            i = 0
        elif v in pos:
            i = pos[v]
        else:
            raise IllegalInference(
                f"read of key {k!r} observed {v!r}, which no txn "
                f"wrote", key=k, value=v)
        if i > 0:
            add("wr", versions[i][0], node)
        if i + 1 < len(versions):
            add("rw", node, versions[i + 1][0])


# ---------------------------------------------------------------------------
# Graph extraction

def extract(history, *, key=None, version_order="write-once",
            init_values=(), realtime=False) -> DepGraph:
    """Infer the dependency graph of a history's ok transactions.

    `key`, `version_order`, `init_values` parameterize txns_of and the
    register order (see module docstring). realtime=True additionally
    emits the dense realtime relation (completion-before-invocation),
    using invocation positions from history.pairs when present (bare ok
    ops — fixtures — fall back to their own index)."""
    history = list(history)
    txns = txns_of(history, key=key)
    ops = [o for o, _ in txns]
    node = {id(o): i for i, o in enumerate(ops)}
    n = len(ops)
    adj = {r: np.zeros((n, n), dtype=bool) for r in RELATIONS}

    def add(rel, i, j):
        if i is not _INIT and j is not _INIT and i != j:
            adj[rel][i, j] = True

    per_key: dict = {}
    for o, t in txns:
        i = node[id(o)]
        for m in t:
            k = mop.key(m)
            slot = per_key.setdefault(
                k, {"appends": [], "writes": [], "reads": []})
            if mop.is_append(m):
                slot["appends"].append((i, mop.value(m)))
            elif mop.is_write(m):
                slot["writes"].append((i, mop.value(m)))
            else:
                slot["reads"].append((i, mop.value(m)))
    for k, slot in per_key.items():
        # a list observation marks an append-mode key even when every
        # append to it fell outside this history slice (read-only keys
        # in a sharded or truncated run)
        reads_lists = any(isinstance(v, (list, tuple))
                          for _, v in slot["reads"])
        if slot["appends"] or reads_lists:
            if slot["writes"]:
                raise IllegalInference(
                    f"key {k!r} saw both append/list-read and write "
                    f"micro-ops", key=k)
            _append_key_edges(k, slot["appends"], slot["reads"], add)
        elif slot["writes"] or slot["reads"]:
            _register_key_edges(
                k, slot["writes"], slot["reads"], add,
                version_order=version_order, init_values=init_values)
    g = DepGraph(ops=ops, adj=adj)
    if realtime:
        g.adj["realtime"] = _realtime(history, ops, node)
    return g


def _realtime(history, ops, node) -> np.ndarray:
    """rt[i, j] iff node i's completion came before node j's
    invocation — both definitely-committed and non-overlapping."""
    n = len(ops)
    call = np.empty(n, dtype=np.int64)
    ret = np.empty(n, dtype=np.int64)
    by_completion = {}
    try:
        for p in _pairs(history):
            if p.completion is not None:
                by_completion[id(p.completion)] = p
    except ValueError:  # malformed pairing: fall back to own indices
        by_completion = {}
    for i, o in enumerate(ops):
        p = by_completion.get(id(o))
        call[i] = p.invoke.index if p is not None else o.index
        ret[i] = o.index
    return ret[:, None] < call[None, :]
