"""Elle-style transactional cycle checker.

The transactional counterpart to checker/linearizable: instead of
searching for a linearization, infer the dependency graph the observed
values force (deps.py — ww/wr/rw/realtime relations, list-append and
rw-register inference) and look for cycles, classified into Adya's
anomalies (anomalies.py — G0/G1c/G-single/G2) via boolean matrix
closure on the supervised engine ladder (ops/closure_tpu.py repeated
squaring -> ops/closure_host.py DFS; checker/supervisor.py
CLOSURE_LADDER).

Usage::

    from jepsen_tpu.checker import cycle
    test["checker"] = cycle.checker(anomalies=["G1c", "G-single"])

A result looks like::

    {"valid": False, "anomaly-types": ["G-single"],
     "anomalies": {"G-single": [{"cycle": [3, 7, 3], "steps": [
         {"from": 3, "to": 7, "rel": "rw"},
         {"from": 7, "to": 3, "rel": "wr"}], ...}]},
     "cycle-count": 1, "node-count": 120, "component-count": 40}

"valid" is False iff any requested anomaly has a cycle; inference
failures (non-prefix reads, duplicate writes, phantom values) degrade
to "unknown" with the offending detail under "error".
"""

from __future__ import annotations

from .. import Checker
from ...history import ops as _ops
from . import deps as _deps
from .anomalies import ANOMALIES, classify
from .deps import DepGraph, IllegalInference, extract

__all__ = [
    "ANOMALIES",
    "CycleChecker",
    "DepGraph",
    "IllegalInference",
    "checker",
    "classify",
    "extract",
]


class CycleChecker(Checker):
    """Dependency-cycle checker over transactional histories.

    anomalies      which Adya anomalies fail the history
    version_order  register-key version order assumption
                   ("write-once" or "value"; list-append keys always
                   recover their order from read prefixes)
    init_values    extra values reads of the initial version may show
                   (e.g. (0,) for the causal counter registers)
    realtime       also infer realtime edges and allow them in cycles
                   (strict serializability flavor)
    engine         None -> supervised closure ladder (the default);
                   "host"/"tpu" pin one engine (parity tooling, bench)
    """

    def __init__(self, anomalies=ANOMALIES, *, version_order="write-once",
                 init_values=(), realtime=False, engine=None,
                 max_witnesses=4):
        for a in anomalies:
            if a not in ANOMALIES:
                raise ValueError(
                    f"unknown anomaly {a!r} (known: {ANOMALIES})")
        self.anomalies = tuple(anomalies)
        self.version_order = version_order
        self.init_values = tuple(init_values)
        self.realtime = realtime
        self.engine = engine
        self.max_witnesses = max_witnesses

    def graph(self, history, key=None) -> DepGraph:
        """The inferred dependency graph (exposed for tests/tools)."""
        return extract(
            history, key=key, version_order=self.version_order,
            init_values=self.init_values, realtime=self.realtime)

    def check(self, test, history, opts=None) -> dict:
        from .. import supervisor as sup_mod

        opts = opts or {}
        history = [self._unwrap(o) for o in _ops(history)]
        sup = sup_mod.get_closure()
        snap0 = sup.telemetry.snapshot()
        budget = (test or {}).get("deadline")
        try:
            g = self.graph(history, key=opts.get("history_key"))
            r = classify(g, self.anomalies, realtime=self.realtime,
                         engine=self.engine,
                         max_witnesses=self.max_witnesses,
                         journal=(test or {}).get("_analysis_journal"),
                         budget=None if budget is None else float(budget))
        except IllegalInference as e:
            return {"valid": "unknown", "error": e.info}
        except sup_mod.EngineFailure as e:
            if e.kind != "deadline":
                raise
            # the client's deadline expired mid-closure: completed
            # components are journaled, so a retry salvages them
            return {"valid": "unknown", "error": "deadline"}
        out = {"valid": not r["anomaly-types"], **r}
        delta = sup_mod.Telemetry.delta(snap0, sup.telemetry.snapshot())
        if any(k != "calls" for k in delta):
            out["supervision"] = delta
        self._render_invalid(test, history, out, opts)
        return out

    @staticmethod
    def _render_invalid(test, history, result, opts) -> None:
        """On a falsified history with a store attached, write a
        timeline with the witness cycles drawn as relation-labeled
        arrows (checker/timeline.py) — the transactional analogue of
        the linearizable checker's counterexample rendering."""
        if result["valid"] is not False:
            return
        if not (test and test.get("name") and test.get("start_time")):
            return
        try:
            from ... import store
            from .. import timeline

            ws = [w for ws in result["anomalies"].values() for w in ws]
            doc = timeline.render(test, history, witness=ws)
            p = store.path_(
                test, list((opts or {}).get("subdirectory") or []),
                "timeline-cycle.html")
            with open(p, "w") as f:
                f.write(doc)
        except Exception:  # noqa: BLE001 — rendering is best-effort
            pass

    @staticmethod
    def _unwrap(o):
        """Unwrap KVTuple txn values when used OUTSIDE independent's
        sharding (a global run over a keyed history): namespace every
        micro-op key with the tuple key so inference stays per-key."""
        # lazy: independent imports checker, so a module-level import
        # here would make the package unimportable whenever independent
        # happens to be the first jepsen_tpu module loaded
        from ...independent import is_tuple
        v = o.value
        if not is_tuple(v) or not isinstance(v.value, (list, tuple)):
            return o
        if not all(_deps.mop.is_op(m) for m in v.value):
            return o
        return o.with_(value=[[m[0], (v.key, m[1]), m[2]]
                              for m in v.value])


def checker(anomalies=ANOMALIES, **kw) -> CycleChecker:
    return CycleChecker(anomalies, **kw)
