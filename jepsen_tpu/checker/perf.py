"""Performance plots from histories (reference: jepsen.checker.perf,
checker/perf.clj). Rendered with matplotlib (Agg) instead of shelling out
to gnuplot — no external binary, and the data prep is vectorized numpy
over the flat history columns rather than per-op seq transforms.

Artifacts written into the test's store dir (or opts["subdirectory"]):

    latency-raw.png        every op as a point, by f and outcome
                           (perf.clj:251-303)
    latency-quantiles.png  0.5/0.95/0.99/1.0 latency quantiles per
                           30s bucket, by f (perf.clj:305-347)
    rate.png               completion throughput per f/outcome in 10s
                           buckets (perf.clj:356-400)

All three shade nemesis activity windows and mark other nemesis events
with vertical lines (perf.clj:171-232).
"""

from __future__ import annotations

import logging
from typing import Mapping

import numpy as np

from ..util import history_latencies, nanos_to_secs, nemesis_intervals
from . import Checker

log = logging.getLogger("jepsen_tpu.checker.perf")

#: outcome colors (perf.clj:164-168)
TYPE_COLORS = {"ok": "#81BFFC", "info": "#FFA400", "fail": "#FF1E90"}
TYPES = ("ok", "info", "fail")

QUANTILES = (0.5, 0.95, 0.99, 1.0)
QUANTILE_COLORS = {0.5: "#81BFFC", 0.95: "#f9b447", 0.99: "#FF1E90",
                   1.0: "#888888"}


def load_pyplot():
    import matplotlib

    matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt

    return plt


def bucket_scale(dt: float, b: np.ndarray | float):
    """Midpoint time of bucket number b (perf.clj:17-21)."""
    return np.floor(b).astype(np.int64) * dt + dt / 2 if isinstance(
        b, np.ndarray
    ) else int(b) * dt + dt / 2


def bucket_time(dt: float, t):
    """Midpoint time of the bucket t falls into (perf.clj:23-27)."""
    return bucket_scale(dt, np.asarray(t) / dt)


def buckets(dt: float, tmax: float) -> np.ndarray:
    """Midpoints of all buckets up to tmax (perf.clj:29-36)."""
    return np.arange(0, tmax // dt + 1) * dt + dt / 2


def quantile_points(dt: float, qs, times, values):
    """{q: (bucket_times, quantile_values)} per time bucket — vectorized
    latencies->quantiles (perf.clj:58-82)."""
    times = np.asarray(times, float)
    values = np.asarray(values, float)
    if len(times) == 0:
        return {}
    mids = bucket_time(dt, times)
    out = {q: ([], []) for q in qs}
    for mid in np.unique(mids):
        vs = values[mids == mid]
        for q in qs:
            # the reference's index quantile: floor(n*q), clamped
            idx = min(len(vs) - 1, int(np.floor(len(vs) * q)))
            out[q][0].append(mid)
            out[q][1].append(np.sort(vs)[idx])
    return out


def _latency_data(history):
    """[(f, outcome, time_s, latency_ms)] for every completed invocation;
    crashed/pending pairs surface as 'info' with no latency point."""
    rows = []
    for rec in history_latencies(history):
        op = rec["op"]
        if not isinstance(op.process, int):
            continue
        comp = rec["completion"]
        outcome = comp.type if comp is not None else "info"
        if rec["latency"] is None:
            continue
        rows.append(
            (str(op.f), outcome, nanos_to_secs(op.time),
             rec["latency"] / 1e6)
        )
    return rows


def nemesis_spans(history):
    """[(start_s, stop_s)] nemesis activity windows; open windows run to
    the end of the history (perf.clj:170-190)."""
    final = 0.0
    for o in reversed(list(history)):
        if o.time is not None and o.time >= 0:
            final = nanos_to_secs(o.time)
            break
    return [
        (nanos_to_secs(start.time),
         nanos_to_secs(stop.time) if stop is not None else final)
        for start, stop in nemesis_intervals(history)
    ]


def nemesis_event_times(history):
    """Times of non-start/stop nemesis ops (perf.clj:206-215)."""
    return [
        nanos_to_secs(o.time)
        for o in history
        if o.process == "nemesis" and o.f not in ("start", "stop")
        and o.time is not None and o.time >= 0
    ]


def _decorate(ax, history, test, title, ylabel):
    for start, stop in nemesis_spans(history):
        ax.axvspan(start, stop, color="black", alpha=0.05, linewidth=0)
    for t in nemesis_event_times(history):
        ax.axvline(t, color="#dddddd", linewidth=1)
    ax.set_title(f"{test.get('name', 'test')} {title}")
    ax.set_xlabel("Time (s)")
    ax.set_ylabel(ylabel)


def out_path(test, opts, filename: str) -> str | None:
    if not (test.get("name") and test.get("start_time")):
        return None
    from .. import store

    return store.path_(test, list((opts or {}).get("subdirectory") or []),
                       filename)


def point_graph(test, history, opts) -> str | None:
    """latency-raw.png (perf.clj:251-303)."""
    rows = _latency_data(history)
    path = out_path(test, opts, "latency-raw.png")
    if not rows or path is None:
        return None
    plt = load_pyplot()
    fig, ax = plt.subplots(figsize=(9, 4), dpi=100)
    fs = sorted({r[0] for r in rows})
    markers = {f: m for f, m in zip(fs, "ox+s^v*D")}
    for f in fs:
        for t in TYPES:
            pts = [(r[2], r[3]) for r in rows if r[0] == f and r[1] == t]
            if not pts:
                continue
            xs, ys = zip(*pts)
            ax.plot(xs, ys, linestyle="", marker=markers[f], markersize=3,
                    color=TYPE_COLORS[t], label=f"{f} {t}")
    ax.set_yscale("log")
    _decorate(ax, history, test, "latency", "Latency (ms)")
    ax.legend(loc="upper right", fontsize=7)
    fig.tight_layout()
    fig.savefig(path)
    plt.close(fig)
    return path


def quantiles_graph(test, history, opts, dt=30, qs=QUANTILES) -> str | None:
    """latency-quantiles.png (perf.clj:305-347)."""
    rows = _latency_data(history)
    path = out_path(test, opts, "latency-quantiles.png")
    if not rows or path is None:
        return None
    plt = load_pyplot()
    fig, ax = plt.subplots(figsize=(9, 4), dpi=100)
    fs = sorted({r[0] for r in rows})
    markers = {f: m for f, m in zip(fs, "ox+s^v*D")}
    for f in fs:
        sub = [(r[2], r[3]) for r in rows if r[0] == f]
        times, lats = zip(*sub)
        for q, (bx, by) in quantile_points(dt, qs, times, lats).items():
            ax.plot(bx, by, marker=markers[f], markersize=3,
                    color=QUANTILE_COLORS.get(q, "#333333"),
                    label=f"{f} {q}")
    ax.set_yscale("log")
    _decorate(ax, history, test, "latency quantiles", "Latency (ms)")
    ax.legend(loc="upper right", fontsize=7)
    fig.tight_layout()
    fig.savefig(path)
    plt.close(fig)
    return path


def rate_graph(test, history, opts, dt=10) -> str | None:
    """rate.png: completion rates by f/outcome (perf.clj:356-400)."""
    rows = [
        (str(o.f), o.type, nanos_to_secs(o.time))
        for o in history
        if not o.is_invoke and isinstance(o.process, int)
        and o.time is not None and o.time >= 0
    ]
    path = out_path(test, opts, "rate.png")
    if not rows or path is None:
        return None
    t_max = max(r[2] for r in rows)
    centers = buckets(dt, t_max)
    plt = load_pyplot()
    fig, ax = plt.subplots(figsize=(9, 4), dpi=100)
    fs = sorted({r[0] for r in rows})
    markers = {f: m for f, m in zip(fs, "ox+s^v*D")}
    for f in fs:
        for t in TYPES:
            times = np.array([r[2] for r in rows if r[0] == f and r[1] == t])
            if len(times) == 0:
                continue
            mids = bucket_time(dt, times)
            ys = [(mids == c).sum() / dt for c in centers]
            ax.plot(centers, ys, marker=markers[f], markersize=3,
                    color=TYPE_COLORS[t], label=f"{f} {t}")
    _decorate(ax, history, test, "rate", "Throughput (hz)")
    ax.legend(loc="upper right", fontsize=7)
    fig.tight_layout()
    fig.savefig(path)
    plt.close(fig)
    return path


# ---------------------------------------------------------------------------
# Checkers (checker.clj:703-724)

class LatencyGraph(Checker):
    """Renders latency-raw + latency-quantiles (checker.clj:703-710)."""

    def check(self, test: Mapping, history, opts=None) -> dict:
        point_graph(test, history, opts)
        quantiles_graph(test, history, opts)
        return {"valid": True}


class RateGraph(Checker):
    """Renders rate.png (checker.clj:712-717)."""

    def check(self, test: Mapping, history, opts=None) -> dict:
        rate_graph(test, history, opts)
        return {"valid": True}


def latency_graph() -> LatencyGraph:
    return LatencyGraph()


def rate_graph_checker() -> RateGraph:
    return RateGraph()


def perf() -> Checker:
    """Composite latency + rate checker (checker.clj:719-724)."""
    from . import compose

    return compose({"latency_graph": latency_graph(),
                    "rate_graph": rate_graph_checker()})
