"""Supervised engine dispatch: deadlines, retry/backoff, circuit
breakers, and the degradation ladder for the checker fleet.

Jepsen's premise is that the *harness* survives the faults it injects,
yet the batch engines it leans on — pallas/Mosaic kernels, the XLA
while-loop kernel, a ctypes C++ library — all sit in front of hardware
and toolchains that fail in practice: device OOM, a wedged first
compile, a TPU preemption mid-batch, a missing g++. Before this module
any such failure aborted the whole analysis. Now every engine call the
linearizable checker makes routes through a Supervisor that gives it:

deadline
    a wall-clock bound enforced by a watchdog thread ON TOP of the
    engines' own step budgets (a while-loop kernel can't consult the
    wall clock; a wedged XLA compile never reaches the kernel at all).
    A timed-out call is abandoned (the worker thread parks on the
    atexit drain — a daemon thread killed mid-XLA-compile aborts the
    interpreter) and counts as an engine failure.

retry
    capped exponential backoff with seeded jitter for transient
    failures, plus adaptive bisection on device OOM: the chunk splits
    in half and the halves retry — a batch one lane too wide for HBM
    degrades into two launches instead of an abort.

circuit breaker
    K consecutive failures quarantine an engine for a cool-down;
    quarantined engines are skipped by the ladder AND by the batch
    routing / calibration in checker/linearizable.py and
    checker/calibrate.py, so a dead backend stops eating a retry
    storm per batch.

degradation ladder
    pallas → tpu → native → host. Every rung computes the same
    verdicts (ops/pcomp + the parity corpus pin this); a failed or
    quarantined rung demotes its chunks to the next one. Chunks that
    already completed keep their verdicts ("salvage") — one engine
    failure never costs more than one chunk of lanes, the same
    locality argument P-compositionality gives the checker itself.

first-compile probe
    a FATAL XLA abort (the Mosaic compiler can take the process down,
    see checker/linearizable.py's racer drain) is contained by probing
    an engine's first compile in a SUBPROCESS; a dead probe merely
    trips the breaker.

Telemetry (retries, demotions, breaker trips, salvaged chunks,
timeouts, bisections) is counted per process and surfaced as a
`supervision` field in checker results and the bench summary line.
"""

from __future__ import annotations

import atexit
import logging
import os
import random
import subprocess
import sys
import threading
import time
from dataclasses import dataclass

log = logging.getLogger("jepsen_tpu.checker.supervisor")

#: The degradation ladder, best rung first. Every rung returns
#: WGLResults with identical verdict semantics. wgl_mesh is the XLA
#: kernel dealt over every addressable device (ops/wgl_tpu mesh path);
#: any mesh failure — device loss, OOM, collective timeout — demotes
#: to the proven single-device rungs, never to a wrong verdict.
LADDER = ("pallas", "wgl_mesh", "tpu", "native", "host")

#: Telemetry counter names (fixed so snapshots/deltas are total).
COUNTERS = (
    "calls", "retries", "demotions", "breaker_trips", "salvaged_chunks",
    "timeouts", "bisections", "engine_failures", "probe_failures",
    "exhausted", "journal_skips", "deadline_expired",
)

# Threads abandoned by watchdog timeouts: same discipline as the
# competition racers in checker/linearizable.py — a daemon thread
# killed mid-XLA-compile aborts the interpreter, so join them (bounded)
# at exit.
_abandoned: list = []


@atexit.register
def _drain_abandoned():
    deadline = time.monotonic() + 120
    for t in _abandoned:
        t.join(timeout=max(0.0, deadline - time.monotonic()))


class EngineFailure(Exception):
    """An engine call failed after supervision gave up on it.

    kind is the final classification: "oom", "timeout", "transient",
    "unavailable", "deadline" (the caller's budget expired — retrying
    or demoting cannot help), or "fatal"."""

    def __init__(self, engine: str, kind: str, cause=None):
        super().__init__(f"{engine} failed ({kind}): {cause}")
        self.engine = engine
        self.kind = kind
        self.cause = cause


class EngineTimeout(Exception):
    """Internal marker: the watchdog expired before the call returned."""


#: substrings that mark an allocation failure on any backend (jaxlib's
#: XlaRuntimeError renders RESOURCE_EXHAUSTED; interpret mode and the
#: native engine raise MemoryError).
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "out of memory", "Out of memory",
                "OOM", "Attempting to allocate")

#: substrings that mark "this engine cannot take this work at all" —
#: not a health event: demote immediately, no retry, no breaker count.
_UNAVAILABLE_MARKERS = ("no int32 encoding", "no kernel model",
                        "no native encoding", "ineligible")


def classify_error(e: BaseException) -> str:
    """Map an engine exception to a retry class: "oom" (bisect then
    retry), "timeout" (retry), "unavailable" (demote, not a health
    event), or "transient" (retry)."""
    if isinstance(e, EngineTimeout):
        return "timeout"
    if isinstance(e, MemoryError):
        return "oom"
    try:
        from ..ops import wgl_native

        if isinstance(e, wgl_native.NativeUnavailable):
            return "unavailable"
    except ImportError:
        pass
    if isinstance(e, ImportError):
        return "unavailable"
    text = f"{type(e).__name__}: {e}"
    if any(m in text for m in _OOM_MARKERS):
        return "oom"
    if any(m in text for m in _UNAVAILABLE_MARKERS):
        return "unavailable"
    return "transient"


@dataclass
class SupervisorConfig:
    """Policy knobs. The defaults are inert on the happy path: no
    watchdog thread unless a deadline exists, no sleeps unless a call
    fails, no subprocess unless probing is enabled."""

    call_timeout: float | None = None  # wall bound per engine call
    #: watchdog slack applied when the CHECKER's time_limit implies a
    #: deadline: engines translate time_limit to step budgets that can
    #: legitimately overshoot (compile time, launch queues), so the
    #: watchdog fires only well past the budget — it exists to catch
    #: wedged calls, not slow ones.
    deadline_slack: float = 4.0
    deadline_grace: float = 60.0
    max_retries: int = 2               # per engine rung, per chunk
    backoff_base: float = 0.05         # seconds; doubles per attempt
    backoff_cap: float = 2.0
    breaker_threshold: int = 3         # K consecutive failures -> open
    breaker_cooldown: float = 30.0     # seconds quarantined
    bisect_min: int = 64               # don't split below this many lanes
    chunk_lanes: int = 8192            # supervision (salvage) granularity
    seed: int = 0                      # backoff jitter rng
    probe_first_compile: bool = False  # subprocess-probe pallas/tpu
    probe_timeout: float = 180.0


class Telemetry:
    """Monotone per-process counters, snapshot/delta-able."""

    def __init__(self):
        self._lock = threading.Lock()
        self._c = {k: 0 for k in COUNTERS}
        self.per_engine: dict = {}  # engine -> {kind: count}

    def record(self, counter: str, n: int = 1) -> None:
        with self._lock:
            self._c[counter] += n

    def record_engine_failure(self, engine: str, kind: str) -> None:
        with self._lock:
            self._c["engine_failures"] += 1
            d = self.per_engine.setdefault(engine, {})
            d[kind] = d.get(kind, 0) + 1

    def snapshot(self) -> dict:
        with self._lock:
            out = dict(self._c)
            out["per_engine"] = {k: dict(v)
                                 for k, v in self.per_engine.items()}
            return out

    @staticmethod
    def delta(before: dict, after: dict) -> dict:
        """after - before, dropping zero counters (and per_engine when
        nothing failed) so quiet calls attach nothing."""
        out = {k: after[k] - before[k] for k in COUNTERS
               if after[k] - before[k]}
        pe = {}
        for eng, kinds in after.get("per_engine", {}).items():
            b = before.get("per_engine", {}).get(eng, {})
            d = {k: v - b.get(k, 0) for k, v in kinds.items()
                 if v - b.get(k, 0)}
            if d:
                pe[eng] = d
        if pe:
            out["per_engine"] = pe
        return out


class CircuitBreaker:
    """Per-engine consecutive-failure breaker with cool-down."""

    def __init__(self, threshold: int, cooldown: float, clock=time.monotonic):
        self.threshold = threshold
        self.cooldown = cooldown
        self.clock = clock
        self._lock = threading.Lock()
        self._consec: dict[str, int] = {}
        self._open_until: dict[str, float] = {}
        # engine -> (claimant thread id, claim expiry): who holds the
        # half-open probe slot once the cool-down has elapsed
        self._half_open: dict[str, tuple] = {}

    def healthy(self, engine: str) -> bool:
        with self._lock:
            until = self._open_until.get(engine)
            if until is None:
                return True
            now = self.clock()
            if now < until:
                return False
            # half-open: the quarantine stays recorded until a probe
            # resolves it, and exactly ONE caller wins the probe slot
            # — concurrent callers racing the cool-down expiry must
            # not all hammer a possibly-still-dead engine. The claim
            # expires after one cool-down so a claimant that died
            # mid-probe cannot wedge routing forever; the same thread
            # may re-consult its own claim (retry loops re-check).
            tid = threading.get_ident()
            claim = self._half_open.get(engine)
            if claim is None or claim[0] == tid or now >= claim[1]:
                self._half_open[engine] = (tid, now + self.cooldown)
                return True
            return False

    def record_success(self, engine: str) -> None:
        with self._lock:
            self._consec[engine] = 0
            self._open_until.pop(engine, None)
            self._half_open.pop(engine, None)

    def record_failure(self, engine: str) -> bool:
        """Count a failure; returns True when this one TRIPS the
        breaker (closed -> open, or a failed half-open probe
        re-tripping)."""
        with self._lock:
            n = self._consec.get(engine, 0) + 1
            self._consec[engine] = n
            until = self._open_until.get(engine)
            if until is not None and self.clock() >= until:
                # the half-open probe failed: re-trip for a full
                # cool-down and free the probe slot
                self._open_until[engine] = self.clock() + self.cooldown
                self._half_open.pop(engine, None)
                return True
            if n >= self.threshold and engine not in self._open_until:
                self._open_until[engine] = self.clock() + self.cooldown
                return True
            return False

    def trip(self, engine: str, cooldown: float | None = None) -> None:
        """Force-quarantine (used by the first-compile probe)."""
        with self._lock:
            self._consec[engine] = max(
                self.threshold, self._consec.get(engine, 0))
            self._open_until[engine] = self.clock() + (
                self.cooldown if cooldown is None else cooldown)
            self._half_open.pop(engine, None)

    def state(self) -> dict:
        with self._lock:
            now = self.clock()
            return {e: round(t - now, 1)
                    for e, t in self._open_until.items() if t > now}


# ---------------------------------------------------------------------------
# Default engine runners & eligibility — one uniform signature:
#   run(model, ess, max_steps=None, time_limit=None) -> list[WGLResult]

def _steps_for(time_limit):
    """time_limit -> a conservative step budget for budget-only engines
    (the wgl_tpu.analysis translation)."""
    from ..ops import wgl_tpu

    return max(1000, int(time_limit * wgl_tpu.STEPS_PER_SEC_ESTIMATE))


def _run_pallas(model, ess, max_steps=None, time_limit=None):
    from ..ops import wgl_pallas_vec

    if max_steps is None and time_limit is not None:
        max_steps = _steps_for(time_limit)
    return list(wgl_pallas_vec.analysis_batch(model, ess,
                                              max_steps=max_steps))


def _run_tpu(model, ess, max_steps=None, time_limit=None):
    from ..ops import wgl_tpu

    if max_steps is None and time_limit is not None:
        max_steps = _steps_for(time_limit)
    kw = {} if max_steps is None else {"max_steps": max_steps}
    return list(wgl_tpu.analysis_batch(model, ess, **kw))


def _run_wgl_mesh(model, ess, max_steps=None, time_limit=None):
    """The XLA search kernel with lane packs sharded over the
    ("keys",) mesh of every addressable device (longest-first dealt,
    empty-lane padded — ops/wgl_tpu.analysis_batch's mesh path)."""
    import jax

    from ..ops import wgl_tpu

    if max_steps is None and time_limit is not None:
        max_steps = _steps_for(time_limit)
    kw = {} if max_steps is None else {"max_steps": max_steps}
    return list(wgl_tpu.analysis_batch(model, ess,
                                       devices=jax.devices(), **kw))


def _run_native(model, ess, max_steps=None, time_limit=None):
    from ..ops import wgl_native

    return wgl_native.analysis_batch(model, ess, max_steps=max_steps,
                                     time_limit=time_limit)


def _run_host(model, ess, max_steps=None, time_limit=None):
    from ..ops import wgl_host

    return [wgl_host.analysis(model, es, max_steps=max_steps,
                              time_limit=time_limit) for es in ess]


def _run_linear(model, ess, max_steps=None, time_limit=None):
    from ..ops import linear as linear_mod

    return [linear_mod.analysis(model, es, time_limit=time_limit)
            for es in ess]


def default_registry() -> dict:
    return {
        "pallas": _run_pallas,
        "wgl_mesh": _run_wgl_mesh,
        "tpu": _run_tpu,
        "native": _run_native,
        "host": _run_host,
        "linear": _run_linear,
    }


# -- closure engines (checker/cycle) ----------------------------------------
#
# The cycle checker's reachability engines ride the same supervision
# machinery — watchdog, retry, breaker, OOM bisection, ladder salvage —
# through a SECOND singleton with its own registry: the work unit is a
# list of adjacency matrices, not (model, entries), and the rung names
# must not collide with the search engines' (probe_engine and the
# breaker key by name). `model` is unused and passed as None.

CLOSURE_LADDER = ("closure_mesh", "closure_tpu", "closure_host")


def _run_closure_mesh(model, adjs, max_steps=None, time_limit=None):
    from ..ops import closure_tpu

    return closure_tpu.reach_batch_mesh(adjs, max_steps=max_steps,
                                        time_limit=time_limit)


def _run_closure_tpu(model, adjs, max_steps=None, time_limit=None):
    from ..ops import closure_tpu

    return closure_tpu.reach_batch(adjs, max_steps=max_steps,
                                   time_limit=time_limit)


def _run_closure_host(model, adjs, max_steps=None, time_limit=None):
    from ..ops import closure_host

    return closure_host.reach_batch(adjs, max_steps=max_steps,
                                    time_limit=time_limit)


# Off-TPU, the XLA squaring engine emulates log2(n) dense matmuls on
# the host — strictly worse than the DFS floor beyond small matrices
# (bench.py cycle_closure measures the real crossover on TPU hosts).
# Eligibility caps its CPU use so big components route straight to
# closure_host without counting as degradation.
CLOSURE_CPU_MAX_N = 256


def _elig_closure_tpu(model, adjs) -> bool:
    try:
        from ..ops import closure_tpu  # noqa: F401 — jax import
    except ImportError:
        return False
    try:
        import jax

        if jax.devices()[0].platform == "tpu":
            return True
    except Exception:  # noqa: BLE001 — no usable backend
        return False
    return all(a.shape[0] <= CLOSURE_CPU_MAX_N for a in adjs)


def _elig_closure_mesh(model, adjs) -> bool:
    """The sharded squaring takes a batch when a mesh exists (>= 2
    devices) AND the batch's biggest matrix clears the calibrated
    mesh-vs-single crossover (checker/calibrate.mesh_min_n) — below
    it, the per-round all-gather costs more than the D-way matmul
    split saves. Off-TPU the same CPU cap as closure_tpu applies, so
    routing (not degradation) sends big emulated work to the host
    DFS."""
    if not _elig_closure_tpu(model, adjs):
        return False
    try:
        import jax

        if jax.device_count() < 2:
            return False
    except Exception:  # noqa: BLE001 — no usable backend
        return False
    from . import calibrate

    return bool(adjs) and max(a.shape[0] for a in adjs) \
        >= calibrate.mesh_min_n()


def closure_registry() -> dict:
    return {
        "closure_mesh": _run_closure_mesh,
        "closure_tpu": _run_closure_tpu,
        "closure_host": _run_closure_host,
    }


def closure_eligibility() -> dict:
    return {
        "closure_mesh": _elig_closure_mesh,
        "closure_tpu": _elig_closure_tpu,
        "closure_host": lambda model, adjs: True,
    }


def _elig_pallas(model, ess) -> bool:
    from ..models import jit as mjit

    try:
        from ..ops import wgl_pallas_vec
    except ImportError:
        return False
    jm = mjit.for_model(model)
    return jm is not None and wgl_pallas_vec.batch_eligible(jm, ess)


def _elig_tpu(model, ess) -> bool:
    from ..models import jit as mjit

    try:
        from ..ops import wgl_tpu  # noqa: F401
    except ImportError:
        return False
    jm = mjit.for_model(model)
    return jm is not None and all(jm.lane_eligible(es) for es in ess)


def _elig_wgl_mesh(model, ess) -> bool:
    """Lane packs shard when a mesh exists and the batch is wide
    enough to be worth dealing (checker/calibrate.mesh_lanes_min —
    below it the per-device chunks are mostly empty-lane padding and
    the single-device launch wins)."""
    if not _elig_tpu(model, ess):
        return False
    try:
        import jax

        n_dev = jax.device_count()
    except Exception:  # noqa: BLE001 — no usable backend
        return False
    if n_dev < 2 or len(ess) < n_dev:
        return False
    from . import calibrate

    return len(ess) >= calibrate.mesh_lanes_min()


def _elig_native(model, ess) -> bool:
    try:
        from ..ops import wgl_native

        wgl_native._get_lib()
        return all(wgl_native.eligible(model, es) for es in ess)
    except Exception:  # noqa: BLE001 — no toolchain / build failure
        return False


def default_eligibility() -> dict:
    return {
        "pallas": _elig_pallas,
        "wgl_mesh": _elig_wgl_mesh,
        "tpu": _elig_tpu,
        "native": _elig_native,
        "host": lambda model, ess: True,
        "linear": lambda model, ess: True,
    }


# ---------------------------------------------------------------------------
# The supervisor

class Supervisor:
    """Fault-tolerant front end over the engine registry. One instance
    per process in production (get()); tests build their own with a
    faulty registry and a tiny config."""

    def __init__(self, config: SupervisorConfig | None = None,
                 registry: dict | None = None,
                 eligibility: dict | None = None,
                 clock=time.monotonic):
        self.config = config or SupervisorConfig()
        self.registry = registry if registry is not None \
            else default_registry()
        self.eligibility = eligibility if eligibility is not None \
            else default_eligibility()
        self.telemetry = Telemetry()
        self.breaker = CircuitBreaker(self.config.breaker_threshold,
                                      self.config.breaker_cooldown,
                                      clock=clock)
        self._rng = random.Random(self.config.seed)
        self._rng_lock = threading.Lock()
        self._probed: dict[str, bool] = {}
        self._probe_lock = threading.Lock()

    # -- health -----------------------------------------------------------

    def healthy(self, engine: str) -> bool:
        """Routing hook: is this engine currently worth attempting?
        Consulted by checker/linearizable's batch routing and by
        checker/calibrate before measuring."""
        return self.breaker.healthy(engine)

    def note_failure(self, engine: str, e: BaseException) -> None:
        """Record an engine failure observed OUTSIDE a supervised call
        (e.g. the native triage loop) so the breaker still learns."""
        kind = classify_error(e)
        if kind == "unavailable":
            return
        self.telemetry.record_engine_failure(engine, kind)
        if self.breaker.record_failure(engine):
            self.telemetry.record("breaker_trips")
            log.warning("circuit breaker tripped for %s (%s)", engine, e)

    def health_snapshot(self) -> dict:
        """One JSON-able view of this supervisor's health for readiness
        endpoints: per-engine healthy/quarantined (with remaining
        cool-down seconds) plus the telemetry counters. `degraded` is
        True when any registered engine is currently quarantined."""
        quarantined = self.breaker.state()
        return {
            "engines": {e: {"healthy": e not in quarantined,
                            **({"cooldown_s": quarantined[e]}
                               if e in quarantined else {})}
                        for e in self.registry},
            "degraded": bool(quarantined),
            "telemetry": self.telemetry.snapshot(),
        }

    # -- single supervised call ------------------------------------------

    def _sleep_backoff(self, attempt: int) -> None:
        c = self.config
        with self._rng_lock:
            jitter = 0.5 + self._rng.random()  # [0.5, 1.5)
        time.sleep(min(c.backoff_cap,
                       c.backoff_base * (2 ** attempt)) * jitter)

    def _bounded(self, fn, engine: str, deadline: float | None):
        """Run fn(), bounded by the watchdog deadline when one exists.
        Timeout abandons the worker thread (atexit-drained) and raises
        EngineTimeout."""
        if deadline is None:
            return fn()
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise EngineTimeout(f"{engine}: deadline already expired")
        box: dict = {}
        done = threading.Event()

        def worker():
            try:
                box["result"] = fn()
            except BaseException as e:  # noqa: BLE001
                box["error"] = e
            done.set()

        t = threading.Thread(target=worker, daemon=True,
                             name=f"jepsen supervised {engine}")
        t.start()
        if not done.wait(remaining):
            _abandoned.append(t)
            self.telemetry.record("timeouts")
            raise EngineTimeout(
                f"{engine}: no verdict within {remaining:.1f}s")
        if "error" in box:
            raise box["error"]
        return box["result"]

    def call(self, engine: str, model, ess, max_steps=None,
             time_limit=None, deadline: float | None = None,
             budget: float | None = None) -> list:
        """One supervised engine call over `ess`: deadline + retries +
        OOM bisection. Success resets the breaker; exhaustion raises
        EngineFailure (callers demote). Results align with `ess`.

        `budget` is an absolute monotonic instant the CLIENT's
        deadline expires at (vs `deadline`, the wedge-catching
        watchdog): the watchdog is capped at budget + deadline_grace,
        and a retry that would start past the budget is skipped —
        backoff sleeps can't blow a deadline the caller promised. A
        budget-expired call raises EngineFailure(kind="deadline")."""
        run = self.registry[engine]
        c = self.config
        if deadline is None and c.call_timeout is not None:
            deadline = time.monotonic() + c.call_timeout
        if budget is not None:
            if time.monotonic() >= budget:
                self.telemetry.record("deadline_expired")
                raise EngineFailure(engine, "deadline",
                                    "budget expired before the call")
            bd = budget + c.deadline_grace
            deadline = bd if deadline is None else min(deadline, bd)
        last = None
        kind = "transient"
        for attempt in range(c.max_retries + 1):
            if attempt:
                if budget is not None and time.monotonic() >= budget:
                    kind = "deadline"
                    self.telemetry.record("deadline_expired")
                    break
                self.telemetry.record("retries")
                self._sleep_backoff(attempt - 1)
            self.telemetry.record("calls")
            try:
                rs = self._bounded(
                    lambda: run(model, ess, max_steps=max_steps,
                                time_limit=time_limit),
                    engine, deadline)
                if len(rs) != len(ess):
                    raise RuntimeError(
                        f"{engine} returned {len(rs)} results for "
                        f"{len(ess)} lanes")
                self.breaker.record_success(engine)
                return rs
            except Exception as e:  # noqa: BLE001 — KeyboardInterrupt
                #                     and SystemExit still propagate
                last, kind = e, classify_error(e)
                if kind == "unavailable":
                    # not a health event: the engine can't take this
                    # work at all — demote without burning retries
                    raise EngineFailure(engine, kind, e) from e
                self.telemetry.record_engine_failure(engine, kind)
                if self.breaker.record_failure(engine):
                    self.telemetry.record("breaker_trips")
                    log.warning("circuit breaker tripped for %s (%s)",
                                engine, e)
                log.warning("%s failed (%s, attempt %d/%d): %s", engine,
                            kind, attempt + 1, c.max_retries + 1, e)
                if kind == "oom" and len(ess) >= 2 * c.bisect_min:
                    # adaptive bisection: halve the chunk and run the
                    # halves (each under its own retry budget) — the
                    # recursive floor is bisect_min
                    self.telemetry.record("bisections")
                    mid = len(ess) // 2
                    return (self.call(engine, model, ess[:mid],
                                      max_steps=max_steps,
                                      time_limit=time_limit,
                                      deadline=deadline, budget=budget)
                            + self.call(engine, model, ess[mid:],
                                        max_steps=max_steps,
                                        time_limit=time_limit,
                                        deadline=deadline,
                                        budget=budget))
                if not self.breaker.healthy(engine):
                    break  # quarantined mid-loop: stop hammering it
        raise EngineFailure(engine, kind, last) from last

    # -- the ladder -------------------------------------------------------

    def _rungs(self, ladder, model, ess) -> list:
        """The ladder filtered to registered engines; host is always
        appended as the floor so the ladder can't be empty."""
        rungs = [r for r in ladder if r in self.registry]
        if "host" in self.registry and "host" not in rungs:
            rungs.append("host")
        return rungs

    def run(self, model, ess, max_steps=None, time_limit=None,
            ladder=LADDER, deadline: float | None = None,
            budget: float | None = None,
            on_exhausted: str = "unknown",
            expired_fill=None) -> list:
        """Run a batch down the degradation ladder in supervision
        chunks. Each chunk starts at the first healthy+eligible rung
        and demotes on failure; completed chunks keep their verdicts
        (salvage). `on_exhausted` decides what happens when a chunk
        falls off the ladder: "unknown" (never abort a batch — the
        auto policy) or "raise" (explicit-algorithm checks, where
        check_safe turns the error into an unknown verdict).

        `budget` (absolute monotonic client deadline) threads into
        every call; once it expires, the remaining chunks resolve to
        `unknown` results tagged ``error="deadline"`` — completed
        chunks keep their verdicts (P-compositional partial salvage)
        and expiry NEVER raises, even under on_exhausted="raise".
        `expired_fill` overrides what an expired lane resolves to (a
        zero-arg callable; the closure ladder passes one, since its
        results are matrices and a fabricated under-approximate
        closure would silently hide anomalies)."""
        from ..ops import wgl_host

        if expired_fill is None:
            def expired_fill():
                r = wgl_host.WGLResult(valid="unknown")
                r.error = "deadline"
                return r

        n = len(ess)
        if n == 0:
            return []
        step = max(1, self.config.chunk_lanes)
        chunks = [list(range(i, min(i + step, n)))
                  for i in range(0, n, step)]
        out: list = [None] * n
        any_demotion = False
        clean_chunks = 0
        expired = False
        for chunk in chunks:
            if not expired and budget is not None \
                    and time.monotonic() >= budget:
                expired = True
                self.telemetry.record("deadline_expired")
            if expired:
                for i in chunk:
                    out[i] = expired_fill()
                continue
            sub = [ess[i] for i in chunk]
            rs = None
            demoted_here = 0
            last_err: EngineFailure | None = None
            for rung in self._rungs(ladder, model, sub):
                if not self.breaker.healthy(rung):
                    # quarantined: demote WITHOUT attempting (the
                    # breaker's whole point); doesn't count as a
                    # demotion unless it changes the outcome rung
                    demoted_here += 1
                    continue
                elig = self.eligibility.get(rung)
                if elig is not None and not elig(model, sub):
                    demoted_here += 1
                    continue
                if (rung in ("pallas", "wgl_mesh", "tpu",
                             "closure_mesh")
                        and self.config.probe_first_compile
                        and not self.probe_engine(rung)):
                    # first compile died in the probe subprocess — the
                    # breaker is tripped; fall through a rung
                    demoted_here += 1
                    continue
                try:
                    rs = self.call(rung, model, sub, max_steps=max_steps,
                                   time_limit=time_limit,
                                   deadline=deadline, budget=budget)
                    break
                except EngineFailure as e:
                    if e.kind == "deadline":
                        # the budget ran out mid-walk: demoting can't
                        # help — resolve this chunk (and, via the
                        # pre-chunk check, the rest) as unknown
                        expired = True
                        break
                    last_err = e
                    demoted_here += 1
                    log.warning("demoting %d lanes below %s (%s)",
                                len(sub), rung, e.kind)
            if rs is None and expired:
                for i in chunk:
                    out[i] = expired_fill()
                continue
            if rs is None:
                self.telemetry.record("exhausted")
                if on_exhausted == "raise":
                    raise last_err or EngineFailure(
                        "ladder", "unavailable",
                        "no engine could take the batch")
                rs = [wgl_host.WGLResult(valid="unknown")
                      for _ in sub]
            # only demotions past the FIRST eligible rung count (a
            # CPU-only host legitimately starts at native/host); the
            # extra eligibility scan is paid only on unclean chunks
            extra = 0
            if demoted_here:
                first = self._first_eligible(ladder, model, sub)
                extra = max(0, demoted_here - first)
            if extra:
                self.telemetry.record("demotions", extra)
                any_demotion = True
            else:
                clean_chunks += 1
            for i, r in zip(chunk, rs):
                out[i] = r
        if any_demotion and clean_chunks:
            # chunks that completed on their first-choice rung while a
            # sibling chunk demoted: their verdicts were salvaged
            # rather than re-run or thrown away
            self.telemetry.record("salvaged_chunks", clean_chunks)
        return out

    def _first_eligible(self, ladder, model, sub) -> int:
        """Index of the first rung that is ELIGIBLE for this work
        regardless of health — the baseline against which demotions
        are counted (ineligible rungs above it are routing, not
        degradation)."""
        for i, rung in enumerate(self._rungs(ladder, model, sub)):
            elig = self.eligibility.get(rung)
            if elig is None or elig(model, sub):
                return i
        return 0

    # -- first-compile probing -------------------------------------------

    def probe_engine(self, engine: str, cmd: list | None = None,
                     timeout: float | None = None) -> bool:
        """Run the engine's first compile in a SUBPROCESS so a FATAL
        abort (Mosaic/XLA can kill the process outright) is contained.
        A failed probe trips the breaker; the result is cached per
        process. `cmd` overrides the probe command (tests)."""
        with self._probe_lock:
            if engine in self._probed:
                return self._probed[engine]
        if cmd is None:
            cmd = [sys.executable, "-c",
                   "from jepsen_tpu.checker import supervisor; "
                   f"supervisor._probe_main({engine!r})"]
        ok = False
        try:
            proc = subprocess.run(
                cmd, capture_output=True, text=True,
                timeout=timeout if timeout is not None
                else self.config.probe_timeout,
                env={**os.environ, "JEPSEN_TPU_PROBE": engine})
            ok = proc.returncode == 0
            if not ok:
                log.warning("first-compile probe for %s died rc=%s: %s",
                            engine, proc.returncode,
                            (proc.stderr or "")[-500:])
        except (subprocess.TimeoutExpired, OSError) as e:
            log.warning("first-compile probe for %s failed: %s", engine, e)
        if not ok:
            self.telemetry.record("probe_failures")
            self.breaker.trip(engine)
            self.telemetry.record("breaker_trips")
        with self._probe_lock:
            self._probed[engine] = ok
        return ok


def _probe_main(engine: str) -> None:
    """Subprocess entry point: compile-and-run the engine's minimal
    lane. Exit status is the probe verdict; a FATAL abort here is
    contained by the parent."""
    from ..ops import closure_tpu, wgl_native, wgl_pallas_vec, wgl_tpu

    probe = {"pallas": wgl_pallas_vec.probe, "tpu": wgl_tpu.probe,
             "wgl_mesh": wgl_tpu.probe_mesh, "native": wgl_native.probe,
             "closure_mesh": closure_tpu.probe_mesh,
             "closure_tpu": closure_tpu.probe}[engine]
    sys.exit(0 if probe() else 1)


# ---------------------------------------------------------------------------
# Per-process singleton

_lock = threading.Lock()
_supervisor: Supervisor | None = None


def _env_config() -> SupervisorConfig:
    """Operator knobs for the default supervisor: JEPSEN_TPU_SUP_PROBE=1
    enables the subprocess first-compile probe (worth its ~seconds of
    child startup on real TPU fleets where a FATAL Mosaic abort costs
    the whole analysis); JEPSEN_TPU_SUP_TIMEOUT=<seconds> sets a hard
    per-call watchdog; JEPSEN_TPU_SUP_GRACE=<seconds> sets the grace
    the watchdog allows an engine past a client budget before
    abandoning the call (deadline_grace — tests shrink it so expiry
    bounds are tight)."""
    cfg = SupervisorConfig()
    if os.environ.get("JEPSEN_TPU_SUP_PROBE") == "1":
        cfg.probe_first_compile = True
    t = os.environ.get("JEPSEN_TPU_SUP_TIMEOUT")
    if t:
        try:
            cfg.call_timeout = float(t)
        except ValueError:
            log.warning("ignoring non-numeric JEPSEN_TPU_SUP_TIMEOUT=%r", t)
    g = os.environ.get("JEPSEN_TPU_SUP_GRACE")
    if g:
        try:
            cfg.deadline_grace = float(g)
        except ValueError:
            log.warning("ignoring non-numeric JEPSEN_TPU_SUP_GRACE=%r", g)
    return cfg


def get() -> Supervisor:
    """The process-wide supervisor the checker routes through."""
    global _supervisor
    with _lock:
        if _supervisor is None:
            _supervisor = Supervisor(_env_config())
        return _supervisor


def _reset_for_tests(sup: Supervisor | None = None) -> None:
    """Swap/clear the singleton (test hook)."""
    global _supervisor
    with _lock:
        _supervisor = sup


_closure_supervisor: Supervisor | None = None


def get_closure() -> Supervisor:
    """The process-wide supervisor for the cycle checker's closure
    engines. Separate from get(): different registry/eligibility, its
    own breaker state, and callers run with ladder=CLOSURE_LADDER +
    on_exhausted="raise" (the "unknown" placeholder path fabricates
    WGL results, which are the wrong type for closures — check_safe
    upstream turns the raise into an unknown verdict instead)."""
    global _closure_supervisor
    with _lock:
        if _closure_supervisor is None:
            _closure_supervisor = Supervisor(
                _env_config(), registry=closure_registry(),
                eligibility=closure_eligibility())
        return _closure_supervisor


def _reset_closure_for_tests(sup: Supervisor | None = None) -> None:
    """Swap/clear the closure singleton (test hook)."""
    global _closure_supervisor
    with _lock:
        _closure_supervisor = sup
