"""Clock-skew-over-time analysis (reference: jepsen.checker.clock,
checker/clock.clj).

The clock nemesis journals {"clock_offsets": {node: seconds}} onto its
ops (nemesis/time.clj:132); this extracts per-node offset series and
plots them as steps, with nemesis windows shaded. Writes clock-skew.png.
"""

from __future__ import annotations

import logging
from typing import Mapping

from ..util import nanos_to_secs
from . import Checker
from .perf import _decorate, load_pyplot, out_path

log = logging.getLogger("jepsen_tpu.checker.clock")


def history_datasets(history) -> dict:
    """{node: ([times_s...], [offsets_s...])} from ops carrying
    clock_offsets (clock.clj:13-34). Each series is extended to the final
    history time so steps render to the end."""
    series: dict = {}
    final = 0.0
    for o in history:
        if o.time is not None and o.time >= 0:
            final = max(final, nanos_to_secs(o.time))
        offsets = o.extra.get("clock_offsets") if o.extra else None
        if offsets is None and isinstance(o.value, dict):
            offsets = o.value.get("clock_offsets")
        if not offsets:
            continue
        t = nanos_to_secs(o.time)
        for node, offset in offsets.items():
            xs, ys = series.setdefault(str(node), ([], []))
            xs.append(t)
            ys.append(float(offset))
    for xs, ys in series.values():
        if xs and xs[-1] < final:
            xs.append(final)
            ys.append(ys[-1])
    return series


def short_node_names(nodes) -> list[str]:
    """Strip common trailing domain components (clock.clj:36-45)."""
    split = [str(n).split(".") for n in nodes]
    if not split:
        return []
    while (
        len(split[0]) > 1
        and all(len(s) > 1 for s in split)
        and len({s[-1] for s in split}) == 1
    ):
        split = [s[:-1] for s in split]
    return [".".join(s) for s in split]


def plot(test, history, opts) -> str | None:
    """clock-skew.png (clock.clj:47-73)."""
    datasets = history_datasets(history)
    path = out_path(test, opts, "clock-skew.png")
    if not datasets or path is None:
        return None
    plt = load_pyplot()
    fig, ax = plt.subplots(figsize=(9, 4), dpi=100)
    nodes = sorted(datasets)
    for node, label in zip(nodes, short_node_names(nodes)):
        xs, ys = datasets[node]
        ax.step(xs, ys, where="post", label=label)
    _decorate(ax, history, test, "clock skew", "Skew (s)")
    ax.legend(loc="upper right", fontsize=7)
    fig.tight_layout()
    fig.savefig(path)
    plt.close(fig)
    return path


class ClockPlot(Checker):
    """Renders the clock-skew plot (checker.clj:726-733)."""

    def check(self, test: Mapping, history, opts=None) -> dict:
        plot(test, history, opts)
        return {"valid": True}


def clock_plot() -> ClockPlot:
    return ClockPlot()
