"""Consistency models as pure state-transition functions.

Parity target: knossos.model (SURVEY.md SS2.2) — `(step model op)` returns
either a new model state or an `Inconsistent`. Here a model is an immutable
object with `step(f, value) -> Model | Inconsistent`; `value` follows the
completed-op convention (a read's value is the value it RETURNED, or None
if unknown).

Every model also declares its *tensor encoding* — how its state packs into
an int32 and how its step function is expressed branchlessly — via
`models.jit`, which is what the TPU search kernel compiles. The host
objects are the semantics oracle; the jitted encodings are tested for
equivalence against them.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Hashable


@dataclass(frozen=True)
class Inconsistent:
    """A model transition that cannot happen (knossos.model/inconsistent)."""

    msg: str


def inconsistent(x: Any) -> bool:
    """knossos.model/inconsistent? parity."""
    return isinstance(x, Inconsistent)


class Model:
    """Base for all models. Subclasses must be immutable and hashable —
    the search memoizes on (linearized-bitset, model-state) pairs
    (knossos.wgl; SURVEY.md SS2.2)."""

    def step(self, f, value):  # -> Model | Inconsistent
        raise NotImplementedError

    def step_op(self, op):
        """Step with an Op or op dict."""
        from ..history import op as to_op

        o = to_op(op)
        return self.step(o.f, o.value)

    def components(self, es):
        """P-compositional decomposition hook ("Faster linearizability
        checking via P-compositionality", Horn & Kroening — PAPERS.md;
        ops/pcomp.py). When this model is a PRODUCT of independent
        sub-objects and every entry of `es` (a history.Entries) touches
        exactly one of them, return a list of

            (sub_model, entry_indices, rewrite)

        components — Herlihy-Wing locality then makes the history
        linearizable iff each component's projection is, and the
        exponential interleaving search collapses into independent
        micro-lanes. `rewrite` is None or an (f, value) -> (f, value)
        mapping applied to projected entries (e.g. a single-key txn
        becomes a plain register op, putting the lane on the batched
        kernel path). An entry that can NEVER linearize and is optional
        (a crashed op with unknown payload) may be dropped from every
        component. Return None when the history doesn't decompose —
        eligibility is structural, decided per history, not per type
        (VERDICT r4 item 6)."""
        return None


@dataclass(frozen=True)
class NoOp(Model):
    """Every operation is fine (knossos.model/noop)."""

    def step(self, f, value):
        return self


@dataclass(frozen=True)
class Register(Model):
    """A read/write register (knossos.model/register). value None = unset."""

    value: Any = None

    def step(self, f, value):
        if f == "write":
            return Register(value)
        if f == "read":
            if value is None or value == self.value:
                return self
            return Inconsistent(
                f"read {value!r} from register holding {self.value!r}"
            )
        return Inconsistent(f"unknown op {f!r}")


@dataclass(frozen=True)
class CASRegister(Model):
    """A compare-and-set register (knossos.model/cas-register): the model
    the north-star search kernel steps (checker.clj:116-141 via
    tests/linearizable_register.clj:35)."""

    value: Any = None

    def step(self, f, value):
        if f == "write":
            return CASRegister(value)
        if f == "cas":
            if value is None:
                return Inconsistent("cas with unknown arguments")
            old, new = value
            if self.value == old:
                return CASRegister(new)
            return Inconsistent(f"can't CAS {self.value!r} from {old!r} to {new!r}")
        if f == "read":
            if value is None or value == self.value:
                return self
            return Inconsistent(
                f"can't read {value!r} from register holding {self.value!r}"
            )
        return Inconsistent(f"unknown op {f!r}")


@dataclass(frozen=True)
class Mutex(Model):
    """A lock (knossos.model/mutex)."""

    locked: bool = False

    def step(self, f, value):
        if f == "acquire":
            if self.locked:
                return Inconsistent("cannot acquire a held lock")
            return Mutex(True)
        if f == "release":
            if not self.locked:
                return Inconsistent("cannot release a free lock")
            return Mutex(False)
        return Inconsistent(f"unknown op {f!r}")


def _freeze_map(d: dict) -> tuple:
    """A canonical (key, value) tuple for a register map, so ==-equal
    maps compare and hash equal in the search memo. Mixed-type
    (unorderable) keys fall back to a type-aware sort key — same
    tradeoff as _freeze_multiset: only memo pruning at stake, never
    soundness."""
    try:
        return tuple(sorted(d.items()))
    except TypeError:
        return tuple(sorted(
            d.items(), key=lambda kv: (type(kv[0]).__name__, repr(kv[0]))))


def _freeze_multiset(items) -> tuple:
    """A canonical tuple for a multiset, so ==-equal pending sets compare
    and hash equal in the search memo. Mixed-type payloads (unorderable)
    fall back to a type-aware sort key — semantically equal multisets may
    then freeze differently across type boundaries, which only costs memo
    pruning, never soundness."""
    try:
        return tuple(sorted(items))
    except TypeError:
        return tuple(sorted(items, key=lambda x: (type(x).__name__, repr(x))))


@dataclass(frozen=True)
class UnorderedQueue(Model):
    """A queue where dequeues may come back in any order
    (knossos.model/unordered-queue). State is a frozen multiset."""

    pending: tuple = ()

    def step(self, f, value):
        if f == "enqueue":
            return UnorderedQueue(_freeze_multiset(self.pending + (value,)))
        if f == "dequeue":
            if value in self.pending:
                items = list(self.pending)
                items.remove(value)
                return UnorderedQueue(_freeze_multiset(items))
            return Inconsistent(f"can't dequeue {value!r}")
        return Inconsistent(f"unknown op {f!r}")

    def components(self, es):
        """By VALUE: the multiset is one counter per value and
        enqueue(v)/dequeue(v) touch only v's counter. A crashed
        dequeue that recorded no value steps to Inconsistent (can
        never linearize) and is optional, so it is semantically absent
        from every linearization and drops. An entry with an op the
        model doesn't know makes its own lane invalid — which is the
        whole history's verdict either way."""
        if self.pending:
            return None
        groups: dict = {}
        try:
            for i, (f, v, crashed) in enumerate(
                    zip(es.f, es.value_out, es.crashed)):
                if f == "dequeue" and crashed and v is None:
                    continue  # can never linearize; optional -> absent
                groups.setdefault(v, []).append(i)
        except TypeError:  # unhashable payload
            return None
        return [(UnorderedQueue(), idx, None)
                for idx in groups.values()]


@dataclass(frozen=True)
class FIFOQueue(Model):
    """A strictly-ordered queue (knossos.model/fifo-queue)."""

    items: tuple = ()

    def step(self, f, value):
        if f == "enqueue":
            return FIFOQueue(self.items + (value,))
        if f == "dequeue":
            if self.items and self.items[0] == value:
                return FIFOQueue(self.items[1:])
            head = self.items[0] if self.items else None
            return Inconsistent(f"expected dequeue of {head!r}, got {value!r}")
        return Inconsistent(f"unknown op {f!r}")


@dataclass(frozen=True)
class MultiRegister(Model):
    """A map of named registers stepped by "txn" ops
    (knossos.model/multi-register — knossos.model parity beyond the
    subset jepsen's own suites use, SURVEY.md SS2.2). The op value is a
    sequence of micro-ops [f, k, v] with f "r"/"read" or "w"/"write",
    applied atomically in order; a read of an unwritten register
    observes its initial value (None unless given in `registers`).

    State is a frozen sorted (key, value) tuple so ==-equal register
    maps hash equal in the search memo."""

    registers: tuple = ()

    def step(self, f, value):
        if f != "txn":
            return Inconsistent(f"unknown op {f!r}")
        if value is None:
            return Inconsistent("txn with unknown micro-ops")
        if not isinstance(value, (list, tuple)):
            return Inconsistent(f"malformed txn payload {value!r}")
        regs = dict(self.registers)
        for micro in value:
            try:
                mf, k, v = micro
            except (TypeError, ValueError):
                return Inconsistent(f"malformed micro-op {micro!r}")
            if mf in ("w", "write"):
                regs[k] = v
            elif mf in ("r", "read"):
                if v is not None and regs.get(k) != v:
                    return Inconsistent(
                        f"read {v!r} from register {k!r} holding "
                        f"{regs.get(k)!r}")
            else:
                return Inconsistent(f"unknown micro-op f {mf!r}")
        return MultiRegister(_freeze_map(regs))

    def components(self, es):
        """By KEY, when every kept entry is a SINGLE-micro-op txn: the
        map is a product of per-key registers and a one-key txn touches
        exactly one of them. Projected entries REWRITE to plain
        register ops ([['w', k, v]] -> write v, [['r', k, v]] -> read
        v), so the micro-lanes get the Register kernel encoding and
        ride the batched TPU path. Multi-micro-op txns couple keys (or
        compose same-key reads/writes atomically) — the history then
        stays on the full search. A crashed txn with no recorded
        micro-ops can never linearize (step -> Inconsistent) and is
        optional, so it drops."""
        inits = dict(self.registers)
        groups: dict = {}
        for i, (f, v, crashed) in enumerate(
                zip(es.f, es.value_out, es.crashed)):
            if crashed and v is None:
                continue  # can never linearize; optional -> absent
            if (f != "txn" or not isinstance(v, (list, tuple))
                    or len(v) != 1):
                return None
            try:
                mf, k, _val = v[0]
            except (TypeError, ValueError):
                return None
            if mf not in ("r", "read", "w", "write"):
                return None
            try:
                groups.setdefault(k, []).append(i)
            except TypeError:  # unhashable key
                return None

        def rewrite(f, value):
            # guards mirror step()'s: the hook validated value_OUT, but
            # rewrite also sees value_IN, and a malformed invoke payload
            # paired with a well-formed completion must degrade to an
            # unconstraining read, not crash the projection (the
            # completed-op convention means the search only ever steps
            # the value_out side)
            if (not isinstance(value, (list, tuple))
                    or len(value) != 1):
                return "read", None
            try:
                mf, _k, val = value[0]
            except (TypeError, ValueError):
                return "read", None
            return (("write", val) if mf in ("w", "write")
                    else ("read", val))

        return [(Register(inits.get(k)), idx, rewrite)
                for k, idx in groups.items()]


@dataclass(frozen=True)
class GrowOnlySet(Model):
    """A set supporting add and read-everything (knossos model/set shape;
    used by set workloads)."""

    items: frozenset = frozenset()

    def step(self, f, value):
        if f == "add":
            return GrowOnlySet(self.items | {value})
        if f == "read":
            if value is None or frozenset(value) == self.items:
                return self
            return Inconsistent(f"read {value!r} but set is {sorted(self.items)!r}")
        return Inconsistent(f"unknown op {f!r}")


# convenience constructors mirroring knossos.model's lowercase fns
def noop() -> NoOp:
    return NoOp()


def register(value=None) -> Register:
    return Register(value)


def cas_register(value=None) -> CASRegister:
    return CASRegister(value)


def mutex() -> Mutex:
    return Mutex()


def unordered_queue() -> UnorderedQueue:
    return UnorderedQueue()


def fifo_queue() -> FIFOQueue:
    return FIFOQueue()
