"""Consistency models as pure state-transition functions.

Parity target: knossos.model (SURVEY.md SS2.2) — `(step model op)` returns
either a new model state or an `Inconsistent`. Here a model is an immutable
object with `step(f, value) -> Model | Inconsistent`; `value` follows the
completed-op convention (a read's value is the value it RETURNED, or None
if unknown).

Every model also declares its *tensor encoding* — how its state packs into
an int32 and how its step function is expressed branchlessly — via
`models.jit`, which is what the TPU search kernel compiles. The host
objects are the semantics oracle; the jitted encodings are tested for
equivalence against them.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Hashable


@dataclass(frozen=True)
class Inconsistent:
    """A model transition that cannot happen (knossos.model/inconsistent)."""

    msg: str


def inconsistent(x: Any) -> bool:
    """knossos.model/inconsistent? parity."""
    return isinstance(x, Inconsistent)


class Model:
    """Base for all models. Subclasses must be immutable and hashable —
    the search memoizes on (linearized-bitset, model-state) pairs
    (knossos.wgl; SURVEY.md SS2.2)."""

    def step(self, f, value):  # -> Model | Inconsistent
        raise NotImplementedError

    def step_op(self, op):
        """Step with an Op or op dict."""
        from ..history import op as to_op

        o = to_op(op)
        return self.step(o.f, o.value)


@dataclass(frozen=True)
class NoOp(Model):
    """Every operation is fine (knossos.model/noop)."""

    def step(self, f, value):
        return self


@dataclass(frozen=True)
class Register(Model):
    """A read/write register (knossos.model/register). value None = unset."""

    value: Any = None

    def step(self, f, value):
        if f == "write":
            return Register(value)
        if f == "read":
            if value is None or value == self.value:
                return self
            return Inconsistent(
                f"read {value!r} from register holding {self.value!r}"
            )
        return Inconsistent(f"unknown op {f!r}")


@dataclass(frozen=True)
class CASRegister(Model):
    """A compare-and-set register (knossos.model/cas-register): the model
    the north-star search kernel steps (checker.clj:116-141 via
    tests/linearizable_register.clj:35)."""

    value: Any = None

    def step(self, f, value):
        if f == "write":
            return CASRegister(value)
        if f == "cas":
            if value is None:
                return Inconsistent("cas with unknown arguments")
            old, new = value
            if self.value == old:
                return CASRegister(new)
            return Inconsistent(f"can't CAS {self.value!r} from {old!r} to {new!r}")
        if f == "read":
            if value is None or value == self.value:
                return self
            return Inconsistent(
                f"can't read {value!r} from register holding {self.value!r}"
            )
        return Inconsistent(f"unknown op {f!r}")


@dataclass(frozen=True)
class Mutex(Model):
    """A lock (knossos.model/mutex)."""

    locked: bool = False

    def step(self, f, value):
        if f == "acquire":
            if self.locked:
                return Inconsistent("cannot acquire a held lock")
            return Mutex(True)
        if f == "release":
            if not self.locked:
                return Inconsistent("cannot release a free lock")
            return Mutex(False)
        return Inconsistent(f"unknown op {f!r}")


def _freeze_multiset(items) -> tuple:
    """A canonical tuple for a multiset, so ==-equal pending sets compare
    and hash equal in the search memo. Mixed-type payloads (unorderable)
    fall back to a type-aware sort key — semantically equal multisets may
    then freeze differently across type boundaries, which only costs memo
    pruning, never soundness."""
    try:
        return tuple(sorted(items))
    except TypeError:
        return tuple(sorted(items, key=lambda x: (type(x).__name__, repr(x))))


@dataclass(frozen=True)
class UnorderedQueue(Model):
    """A queue where dequeues may come back in any order
    (knossos.model/unordered-queue). State is a frozen multiset."""

    pending: tuple = ()

    def step(self, f, value):
        if f == "enqueue":
            return UnorderedQueue(_freeze_multiset(self.pending + (value,)))
        if f == "dequeue":
            if value in self.pending:
                items = list(self.pending)
                items.remove(value)
                return UnorderedQueue(_freeze_multiset(items))
            return Inconsistent(f"can't dequeue {value!r}")
        return Inconsistent(f"unknown op {f!r}")


@dataclass(frozen=True)
class FIFOQueue(Model):
    """A strictly-ordered queue (knossos.model/fifo-queue)."""

    items: tuple = ()

    def step(self, f, value):
        if f == "enqueue":
            return FIFOQueue(self.items + (value,))
        if f == "dequeue":
            if self.items and self.items[0] == value:
                return FIFOQueue(self.items[1:])
            head = self.items[0] if self.items else None
            return Inconsistent(f"expected dequeue of {head!r}, got {value!r}")
        return Inconsistent(f"unknown op {f!r}")


@dataclass(frozen=True)
class GrowOnlySet(Model):
    """A set supporting add and read-everything (knossos model/set shape;
    used by set workloads)."""

    items: frozenset = frozenset()

    def step(self, f, value):
        if f == "add":
            return GrowOnlySet(self.items | {value})
        if f == "read":
            if value is None or frozenset(value) == self.items:
                return self
            return Inconsistent(f"read {value!r} but set is {sorted(self.items)!r}")
        return Inconsistent(f"unknown op {f!r}")


# convenience constructors mirroring knossos.model's lowercase fns
def noop() -> NoOp:
    return NoOp()


def register(value=None) -> Register:
    return Register(value)


def cas_register(value=None) -> CASRegister:
    return CASRegister(value)


def mutex() -> Mutex:
    return Mutex()


def unordered_queue() -> UnorderedQueue:
    return UnorderedQueue()


def fifo_queue() -> FIFOQueue:
    return FIFOQueue()
