"""Int-encoded, jit-compilable model step functions.

The TPU linearizability search (ops/wgl.py) can't step Python objects: it
needs the model as a branchless int32 transition function

    step(state: int32, f: int32, v1: int32, v2: int32) -> (state', ok: bool)

compiled straight into the search kernel (BASELINE.json north star: "the
knossos.model state-transition function JIT-compiled"). Each `JitModel`
packs a host model's state into an int32 scalar and mirrors its semantics
exactly; tests/test_models.py checks equivalence against the host oracle
in jepsen_tpu.models.

Value sentinel: NIL32 marks "unknown/absent" (a crashed read's value, an
unset register). Payload values must fit in int32 and stay below NIL32 —
the encoder in ops/wgl.py enforces this and falls back to the host search
otherwise.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Sequence

import jax.numpy as jnp
import numpy as np

NIL32 = np.int32(2**30)


@dataclass(frozen=True)
class JitModel:
    """A model expressed as an int32 transition function.

    fs: f-name -> code mapping used by the encoder (must match the
    workload's FSchema ordering).
    """

    name: str
    fs: tuple
    init_state: int
    step: Callable  # (state, f, v1, v2) -> (state', ok)

    def f_code(self, f) -> int:
        return self.fs.index(f)


def _cas_register_step(state, f, v1, v2):
    # f: 0=read 1=write 2=cas (REGISTER_SCHEMA order); f == -1
    # (unknown/malformed op) falls through every branch to ok=False
    is_read = f == 0
    is_write = f == 1
    is_cas = f == 2
    match = state == v1
    ok = jnp.where(
        is_read,
        (v1 == NIL32) | match,
        jnp.where(is_write, True, is_cas & match),
    )
    new_state = jnp.where(
        is_write, v1, jnp.where(is_cas & match, v2, state)
    )
    return new_state, ok


cas_register = JitModel(
    name="cas-register",
    fs=("read", "write", "cas"),
    init_state=int(NIL32),  # unset
    step=_cas_register_step,
)


def _register_step(state, f, v1, v2):
    # f: 0=read 1=write; f == -1 (unknown/malformed op) is never ok
    is_read = f == 0
    is_write = f == 1
    ok = jnp.where(is_write, True, is_read & ((v1 == NIL32) | (state == v1)))
    new_state = jnp.where(is_write, v1, state)
    return new_state, ok


register = JitModel(
    name="register",
    fs=("read", "write"),
    init_state=int(NIL32),
    step=_register_step,
)


def _mutex_step(state, f, v1, v2):
    # f: 0=acquire 1=release; state: 0=free 1=held; f == -1 never ok
    is_acquire = f == 0
    is_release = f == 1
    ok = jnp.where(is_acquire, state == 0, is_release & (state == 1))
    new_state = jnp.where(ok, jnp.where(is_acquire, 1, 0), state)
    return new_state, ok


mutex = JitModel(
    name="mutex",
    fs=("acquire", "release"),
    init_state=0,
    step=_mutex_step,
)


BY_NAME = {m.name: m for m in (cas_register, register, mutex)}


def for_model(model) -> JitModel | None:
    """The JitModel equivalent of a host model instance (fresh state only),
    or None if the model has no scalar int encoding (queues, sets) — the
    checker then uses the host search path."""
    from . import CASRegister, Mutex, Register

    if isinstance(model, CASRegister) and model.value is None:
        return cas_register
    if isinstance(model, Register) and model.value is None:
        return register
    if isinstance(model, Mutex) and not model.locked:
        return mutex
    return None


def encode_value(v) -> int:
    """Encode one payload scalar for the kernel; None -> NIL32. Only true
    integers are encodable — floats/strings would be silently truncated
    or coerced, letting the kernel accept histories the host model
    rejects, so they raise instead (the checker then uses the host
    search)."""
    if v is None:
        return int(NIL32)
    import numbers

    if not isinstance(v, numbers.Integral):
        raise TypeError(f"value {v!r} has no int32 kernel encoding")
    v = int(v)
    if not (-(2**30) < v < 2**30):
        raise OverflowError(f"value {v} does not fit the int32 kernel encoding")
    return v
