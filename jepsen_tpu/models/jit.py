"""Int-encoded, jit-compilable model step functions.

The TPU linearizability search (ops/wgl_tpu.py) can't step Python
objects: it needs the model as a branchless int32 transition function
compiled straight into the search kernel (BASELINE.json north star: "the
knossos.model state-transition function JIT-compiled"). Each kernel
model packs a host model's state into a fixed int32 VECTOR and mirrors
its semantics exactly; tests/test_models.py checks equivalence against
the host oracle in jepsen_tpu.models.

Two families:

- Scalar models (register / cas-register / mutex): state is one int32
  (a width-1 vector in the kernel), values are encoded globally via
  `encode_value` (ints only), and the memo key is (bitset, state).
- The unordered-queue model (knossos.model/unordered-queue): state is a
  COUNT VECTOR over the lane's distinct values — each lane builds its
  own value -> slot mapping, so any hashable payloads work, not just
  ints. Two structural facts make it as cheap as the scalar models:
  the multiset state is a pure function of WHICH ops are linearized
  (order-independent), so the memo key is the bitset alone
  (state_in_key=False); and enqueue/dequeue are exactly invertible, so
  backtracking applies `unstep` instead of storing a state snapshot per
  DFS depth (has_unstep=True).

Value sentinel: NIL32 marks "unknown/absent" (a crashed read's value, an
unset register). Scalar payload values must fit in int32 and stay below
NIL32 — `lane_eligible` enforces this and the checker falls back to the
host search otherwise.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Sequence

import jax.numpy as jnp
import numpy as np

NIL32 = np.int32(2**30)

# (f, value) -> (f_code, v1, v2) per scalar model — see
# JitModel.encode_lane. Bounded in practice by the value universe of
# the histories checked; entries are 3-int tuples.
_ENCODE_CACHE: dict = {}


@dataclass(frozen=True)
class JitModel:
    """A model expressed as an int32 scalar transition function.

    fs: f-name -> code mapping used by the encoder (must match the
    workload's FSchema ordering).

    The kernel-facing interface (vec_step / init_vec / encode_entry /
    lane_*) presents this as a width-1 vector model so the TPU search
    compiles one uniform kernel shape for all models.
    """

    name: str
    fs: tuple
    init_state: int
    step: Callable  # (state, f, v1, v2) -> (state', ok)

    # memo key is (bitset, state); no inverse step (writes destroy state)
    state_in_key = True
    has_unstep = False

    def f_code(self, f) -> int:
        return self.fs.index(f)

    # ---- kernel interface ----

    def lane_width(self, es) -> int:
        return 1

    def lane_codec(self, es) -> Callable:
        return encode_value

    def lane_eligible(self, es) -> bool:
        """Every payload in `es` has an int32 encoding. Memoized on the
        Entries instance: the checker probes eligibility once for
        routing and the engines re-check before packing, and at
        many-thousand-lane batch shapes the per-entry Python scan was
        the single largest host cost (r5 profile: ~7 s of a 12 s
        16k-lane check)."""
        cached = getattr(es, "_lane_elig", None)
        if cached is not None and cached[0] == self.name:
            return cached[1]
        ok = self._lane_eligible(es)
        try:
            es._lane_elig = (self.name, ok)
        except AttributeError:  # not an Entries (e.g. a test stub)
            pass
        return ok

    def _lane_eligible(self, es) -> bool:
        for f, v in zip(es.f, es.value_out):
            if f not in self.fs:
                continue  # encoded as never-linearizable, value unused
            try:
                if isinstance(v, (tuple, list)):
                    for x in v:
                        encode_value(x)
                else:
                    encode_value(v)
            except (OverflowError, TypeError, ValueError):
                return False
        return True

    def init_vec(self, width: int) -> np.ndarray:
        assert width >= 1
        out = np.zeros(width, np.int32)
        out[0] = self.init_state
        return out

    def encode_entry(self, fname, val, codec) -> tuple:
        """-> (f_code, v1, v2) for one entry. Ops the host model can
        NEVER linearize (unknown :f, or a cas with unknown arguments ->
        Inconsistent) encode as f = -1: every step maps -1 to ok=False,
        the exact kernel image of Inconsistent."""
        if fname not in self.fs or (fname == "cas" and val is None):
            return -1, int(NIL32), int(NIL32)
        if isinstance(val, (tuple, list)):
            v1 = codec(val[0] if len(val) > 0 else None)
            v2 = codec(val[1] if len(val) > 1 else None)
        else:
            v1, v2 = codec(val), int(NIL32)
        return self.f_code(fname), v1, v2

    def vec_step(self, state, f, v1, v2):
        s, ok = self.step(state[0], f, v1, v2)
        return state.at[0].set(s.astype(jnp.int32)), ok

    def vec_unstep(self, state, f, v1, v2):
        raise NotImplementedError(f"{self.name} has no inverse step")

    def vec_canon(self, state):
        """State vector as it enters the memo key — identity for models
        whose vector IS the logical state."""
        return state

    def encode_lane(self, es) -> tuple:
        """(f, v1, v2) int32 arrays for a whole lane in one pass.

        Scalar models use the GLOBAL value codec, so (f, value) ->
        encoding is memoizable across lanes and batches — histories
        repeat a small value universe heavily, and the per-op
        encode_entry call is the dominant host cost when packing
        thousands of lanes (BENCH tpu-vs-native). Unhashable payloads
        fall through to the uncached path."""
        n = len(es)
        f = np.empty(n, np.int32)
        v1 = np.empty(n, np.int32)
        v2 = np.empty(n, np.int32)
        cache = _ENCODE_CACHE.setdefault(self.name, {})
        enc = self.encode_entry
        for e, (fn, val) in enumerate(zip(es.f, es.value_out)):
            try:
                key = (fn, val) if not isinstance(val, list) \
                    else (fn, tuple(val))
                t = cache.get(key)
                if t is None:
                    t = enc(fn, val, encode_value)
                    cache[key] = t
            except TypeError:  # unhashable payload
                t = enc(fn, val, encode_value)
            f[e], v1[e], v2[e] = t
        return f, v1, v2

    def encode_batch(self, entries_list, total: int) -> tuple:
        """Flat (f, v1, v2) arrays over a whole BATCH of lanes in one
        pass, interning distinct (f, value) pairs so each is encoded
        once and the expansion is a single table gather. At 4096-lane
        pack shapes the per-entry Python loop in encode_lane is the
        host-side bottleneck (~0.65us/entry); interning roughly halves
        it. Scalar models only (the global value codec makes pairs
        shareable across lanes). Raises TypeError on unhashable
        payloads — callers fall back to encode_lane per lane."""
        keymap: dict = {}
        firsts: list = []

        def kid(fn, val):
            k = (fn, tuple(val)) if type(val) is list else (fn, val)
            i = keymap.get(k)
            if i is None:
                i = len(keymap)
                keymap[k] = i
                firsts.append((fn, val))
            return i

        ids = np.fromiter(
            (kid(fn, val) for es in entries_list
             for fn, val in zip(es.f, es.value_out)),
            np.int64, total)
        # the distinct-pair encodings go through the same module-level
        # cache encode_lane uses — one memoization mechanism, shared
        # across batches and both entry points
        cache = _ENCODE_CACHE.setdefault(self.name, {})
        enc = self.encode_entry

        def one(fn, val):
            k = (fn, tuple(val)) if type(val) is list else (fn, val)
            t = cache.get(k)
            if t is None:
                t = enc(fn, val, encode_value)
                cache[k] = t
            return t

        table = np.array(
            [one(fn, val) for fn, val in firsts],
            np.int32).reshape(len(firsts), 3)
        t = table[ids]
        return (np.ascontiguousarray(t[:, 0]),
                np.ascontiguousarray(t[:, 1]),
                np.ascontiguousarray(t[:, 2]))


def _cas_register_step(state, f, v1, v2):
    # f: 0=read 1=write 2=cas (REGISTER_SCHEMA order); f == -1
    # (unknown/malformed op) falls through every branch to ok=False
    is_read = f == 0
    is_write = f == 1
    is_cas = f == 2
    match = state == v1
    # pure boolean algebra (no where-with-literal-True): Mosaic's
    # vector lowering rejects the i8->i1 truncation a splat True
    # select produces, and the algebra is identical — f == -1 falls
    # through every branch to ok=False
    ok = (is_read & ((v1 == NIL32) | match)) | is_write | (is_cas & match)
    new_state = jnp.where(
        is_write, v1, jnp.where(is_cas & match, v2, state)
    )
    return new_state, ok


cas_register = JitModel(
    name="cas-register",
    fs=("read", "write", "cas"),
    init_state=int(NIL32),  # unset
    step=_cas_register_step,
)


def _register_step(state, f, v1, v2):
    # f: 0=read 1=write; f == -1 (unknown/malformed op) is never ok
    is_read = f == 0
    is_write = f == 1
    ok = is_write | (is_read & ((v1 == NIL32) | (state == v1)))
    new_state = jnp.where(is_write, v1, state)
    return new_state, ok


register = JitModel(
    name="register",
    fs=("read", "write"),
    init_state=int(NIL32),
    step=_register_step,
)


def _mutex_step(state, f, v1, v2):
    # f: 0=acquire 1=release; state: 0=free 1=held; f == -1 never ok
    is_acquire = f == 0
    is_release = f == 1
    ok = (is_acquire & (state == 0)) | (is_release & (state == 1))
    new_state = jnp.where(ok, jnp.where(is_acquire, 1, 0), state)
    return new_state, ok


mutex = JitModel(
    name="mutex",
    fs=("acquire", "release"),
    init_state=0,
    step=_mutex_step,
)


@dataclass(frozen=True)
class QueueJitModel:
    """knossos.model/unordered-queue as a count-vector kernel model.

    State is int32[width] where slot i counts how many copies of the
    lane's i-th distinct value are pending. Per-lane value -> slot
    mapping comes from a dict walk of the history (lane_codec), so any
    hashable payloads work and cross-type equality (1 == 1.0) matches
    the host model's `value in pending` semantics exactly.

    state_in_key=False: the multiset is determined by WHICH entries are
    linearized (each linearized enqueue adds its value, each dequeue
    removes it — order never matters), so the bitset alone is a complete
    memo key. has_unstep=True: backtracking an enqueue decrements its
    slot, a dequeue increments it — no per-depth state snapshots.
    """

    name: str = "unordered-queue"
    fs: tuple = ("enqueue", "dequeue")

    state_in_key = False
    has_unstep = True

    def f_code(self, f) -> int:
        return self.fs.index(f)

    def _universe(self, es) -> dict:
        """value -> slot over every enqueue/dequeue payload in the lane
        (insertion order; dict equality collapses ==-equal values just
        like the host model's multiset membership test). Memoized on
        the Entries instance: routing (batch_eligible), state sizing
        (_state_pad -> lane_width) and packing (lane_codec) each need
        it, and the dict walk is the queue family's dominant per-lane
        host cost at many-thousand-lane batch shapes."""
        cached = getattr(es, "_q_universe", None)
        if cached is not None:
            return cached
        m: dict = {}
        for f, v in zip(es.f, es.value_out):
            if f in self.fs and v not in m:
                m[v] = len(m)
        try:
            es._q_universe = m
        except AttributeError:  # not an Entries (e.g. a test stub)
            pass
        return m

    def lane_width(self, es) -> int:
        return max(1, len(self._universe(es)))

    def lane_codec(self, es) -> Callable:
        m = self._universe(es)
        return lambda v: m[v]

    def lane_eligible(self, es) -> bool:
        """Eligible iff every queue payload is hashable (unhashable
        values can't index the slot map; the host path handles them).
        Memoized on the Entries instance like JitModel.lane_eligible —
        the dict walk is the queue's per-lane pack cost and the checker
        re-probes it for routing."""
        cached = getattr(es, "_lane_elig", None)
        if cached is not None and cached[0] == self.name:
            return cached[1]
        try:
            self._universe(es)
            ok = True
        except TypeError:
            ok = False
        try:
            es._lane_elig = (self.name, ok)
        except AttributeError:
            pass
        return ok

    def init_vec(self, width: int) -> np.ndarray:
        return np.zeros(width, np.int32)

    def encode_entry(self, fname, val, codec) -> tuple:
        if fname not in self.fs:
            return -1, int(NIL32), int(NIL32)
        return self.f_code(fname), codec(val), int(NIL32)

    def encode_lane(self, es) -> tuple:
        """(f, v1, v2) int32 arrays for a whole lane. The queue codec is
        PER LANE (value -> slot map), so nothing is memoizable across
        lanes; this is just the loop without per-call dispatch."""
        n = len(es)
        f = np.empty(n, np.int32)
        v1 = np.empty(n, np.int32)
        v2 = np.empty(n, np.int32)
        codec = self.lane_codec(es)
        for e, (fn, val) in enumerate(zip(es.f, es.value_out)):
            f[e], v1[e], v2[e] = self.encode_entry(fn, val, codec)
        return f, v1, v2

    def vec_step(self, state, f, v1, v2):
        # f: 0=enqueue 1=dequeue; v1 = slot index. f == -1 never ok.
        is_enq = f == 0
        is_deq = f == 1
        slot = jnp.clip(v1, 0, state.shape[0] - 1)
        ok = jnp.where(is_enq, True, is_deq & (state[slot] > 0))
        delta = jnp.where(ok & is_enq, 1, 0) - jnp.where(ok & is_deq, 1, 0)
        return state.at[slot].add(delta.astype(jnp.int32)), ok

    def vec_unstep(self, state, f, v1, v2):
        # exact inverse of an APPLIED (ok) transition
        slot = jnp.clip(v1, 0, state.shape[0] - 1)
        delta = jnp.where(f == 0, -1, 1)
        return state.at[slot].add(delta.astype(jnp.int32))

unordered_queue = QueueJitModel()


@dataclass(frozen=True)
class FifoQueueJitModel(QueueJitModel):
    """knossos.model/fifo-queue as a ring-buffer kernel model. Shares
    the per-lane value-universe codec and encoding machinery with
    QueueJitModel; only state layout and transitions differ.

    State is int32[W+2]: W buffer slots holding encoded value ids in
    enqueue order, then head and tail cursors. W = the lane's enqueue
    count, the most values that can ever be pending at once. Enqueue
    writes buf[tail], tail+=1; dequeue is ok iff head<tail and
    buf[head] == v, head+=1 (the value stays in place).

    Order matters, so the memo key includes the state — canonicalized
    by vec_canon so representationally different vectors with the same
    logical queue share a key. Both transitions are exactly invertible
    (cursor decrements; dequeue never clears its slot, and enqueues
    only ever write at tail >= head so a popped dequeue's value is
    still in buf[head-1]), so has_unstep=True and the kernel skips the
    per-depth state-snapshot stack."""

    name: str = "fifo-queue"

    state_in_key = True
    has_unstep = True

    def lane_width(self, es) -> int:
        n_enq = sum(1 for f in es.f if f == "enqueue")
        return max(1, n_enq) + 2

    def vec_step(self, state, f, v1, v2):
        w = state.shape[0] - 2
        head, tail = state[w], state[w + 1]
        is_enq = f == 0
        is_deq = f == 1
        front = state[jnp.clip(head, 0, w - 1)]
        enq_ok = is_enq & (tail < w)
        deq_ok = is_deq & (head < tail) & (front == v1)
        ok = enq_ok | deq_ok
        slot = jnp.clip(tail, 0, w - 1)
        state = state.at[slot].set(
            jnp.where(enq_ok, v1, state[slot]).astype(jnp.int32))
        state = state.at[w].set(
            (head + jnp.where(deq_ok, 1, 0)).astype(jnp.int32))
        state = state.at[w + 1].set(
            (tail + jnp.where(enq_ok, 1, 0)).astype(jnp.int32))
        return state, ok

    def vec_unstep(self, state, f, v1, v2):
        # exact inverse of an APPLIED (ok) transition
        w = state.shape[0] - 2
        delta_enq = jnp.where(f == 0, 1, 0)
        delta_deq = jnp.where(f == 1, 1, 0)
        state = state.at[w].set(
            (state[w] - delta_deq).astype(jnp.int32))
        state = state.at[w + 1].set(
            (state[w + 1] - delta_enq).astype(jnp.int32))
        return state

    def vec_canon(self, state):
        """Memo keys must encode the LOGICAL queue — (head, tail)
        offsets and dead slots are representation. Shift the live
        window to offset 0 and zero everything else, so memo behavior
        (and step counts) matches the host search exactly."""
        w = state.shape[0] - 2
        head, tail = state[w], state[w + 1]
        count = tail - head
        rolled = jnp.roll(state[:w], -head)
        live = jnp.arange(w) < count
        buf = jnp.where(live, rolled, 0).astype(jnp.int32)
        out = jnp.concatenate(
            [buf, jnp.stack([count, jnp.zeros_like(count)])])
        return out.astype(jnp.int32)


fifo_queue = FifoQueueJitModel()


BY_NAME = {
    m.name: m
    for m in (cas_register, register, mutex, unordered_queue, fifo_queue)
}


def for_model(model):
    """The kernel-model equivalent of a host model instance (fresh state
    only), or None if the model has no kernel encoding (sets) — the
    checker then uses the host search path."""
    from . import CASRegister, FIFOQueue, Mutex, Register, UnorderedQueue

    if isinstance(model, CASRegister) and model.value is None:
        return cas_register
    if isinstance(model, Register) and model.value is None:
        return register
    if isinstance(model, Mutex) and not model.locked:
        return mutex
    if isinstance(model, UnorderedQueue) and not model.pending:
        return unordered_queue
    if isinstance(model, FIFOQueue) and not model.items:
        return fifo_queue
    return None


def encode_value(v) -> int:
    """Encode one payload scalar for the kernel; None -> NIL32. Only true
    integers are encodable — floats/strings would be silently truncated
    or coerced, letting the kernel accept histories the host model
    rejects, so they raise instead (the checker then uses the host
    search). The `type(v) is int` fast path matters: this runs per
    payload per lane at pack time, and the numbers.Integral ABC
    dispatch alone was ~2.5 s of a 16k-lane batch check (r5 profile)."""
    if type(v) is int:
        if -1073741824 < v < 1073741824:  # +-2**30
            return v
        raise OverflowError(
            f"value {v} does not fit the int32 kernel encoding")
    if v is None:
        return int(NIL32)
    import numbers

    if not isinstance(v, numbers.Integral):
        raise TypeError(f"value {v!r} has no int32 kernel encoding")
    v = int(v)
    if not (-(2**30) < v < 2**30):
        raise OverflowError(f"value {v} does not fit the int32 kernel encoding")
    return v
