// Native Wing–Gong–Lowe linearizability search.
//
// The TPU-era answer to the reference's compute plane being a JVM with
// a 32 GB heap (knossos, jepsen/project.clj:30): histories whose model
// has an int32 kernel encoding but can't ride the TPU kernel (or when
// no accelerator is attached) are searched here instead of in pure
// Python — same algorithm as ops/wgl_host.py (Lowe's linked-list
// just-lift search with a (bitset, state) memo), GIL-free and ~100×
// the Python fallback's speed.
//
// Models mirror models/jit.py's int32 encodings exactly:
//   0 cas-register  state: int32 scalar, NIL32 = unset
//   1 register
//   2 mutex
//   3 unordered-queue  state: int32[width] slot counts; memo key is the
//     bitset alone (the multiset is a function of WHICH entries are
//     linearized), and backtracking inverts the step instead of
//     snapshotting.
//
// Build: g++ -O2 -shared -fPIC -o libwglsearch.so wgl_search.cpp
// Driven via ctypes from ops/wgl_native.py.

#include <chrono>
#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_set>
#include <vector>

namespace {

constexpr int32_t kNil32 = 1 << 30;  // models/jit.py NIL32

enum Verdict { kFalse = 0, kTrue = 1, kUnknown = 2 };

struct Stepper {
  int kind;
  int width;  // queue state width (scalars: 1)

  // state[0] for scalars; full vector for the queue.
  // Returns ok; mutates state in place only when ok.
  bool step(std::vector<int32_t>& state, int32_t f, int32_t v1,
            int32_t v2) const {
    switch (kind) {
      case 0: {  // cas-register: 0=read 1=write 2=cas
        if (f == 0) {
          return v1 == kNil32 || state[0] == v1;
        }
        if (f == 1) {
          state[0] = v1;
          return true;
        }
        if (f == 2 && state[0] == v1) {
          state[0] = v2;
          return true;
        }
        return false;
      }
      case 1: {  // register: 0=read 1=write
        if (f == 1) {
          state[0] = v1;
          return true;
        }
        return f == 0 && (v1 == kNil32 || state[0] == v1);
      }
      case 2: {  // mutex: 0=acquire 1=release; state 0 free / 1 held
        if (f == 0 && state[0] == 0) {
          state[0] = 1;
          return true;
        }
        if (f == 1 && state[0] == 1) {
          state[0] = 0;
          return true;
        }
        return false;
      }
      case 3: {  // unordered-queue: 0=enqueue 1=dequeue; v1 = slot
        if (v1 < 0 || v1 >= width) return false;
        if (f == 0) {
          state[v1] += 1;
          return true;
        }
        if (f == 1 && state[v1] > 0) {
          state[v1] -= 1;
          return true;
        }
        return false;
      }
      case 4: {  // fifo-queue ring buffer: [buf(width-2), head, tail]
        const int w = width - 2;
        int32_t& head = state[w];
        int32_t& tail = state[w + 1];
        if (f == 0) {  // enqueue v1 at the tail
          if (tail >= w || v1 < 0) return false;
          state[tail] = v1;
          tail += 1;
          return true;
        }
        if (f == 1 && head < tail && state[head] == v1) {
          head += 1;  // value stays in place (needed by unstep)
          return true;
        }
        return false;
      }
      default:
        return false;
    }
  }

  void unstep(std::vector<int32_t>& state, int32_t f, int32_t v1) const {
    // has_unstep kinds only: exact inverse of an APPLIED transition
    if (kind == 3) {
      if (f == 0)
        state[v1] -= 1;
      else
        state[v1] += 1;
      return;
    }
    // fifo-queue: enqueue pops the tail, dequeue restores the head —
    // buf[head-1] still holds the dequeued value (never overwritten,
    // enqueues only write at tail >= head)
    const int w = width - 2;
    if (f == 0)
      state[w + 1] -= 1;
    else
      state[w] -= 1;
  }

  bool state_in_key() const { return kind != 3; }
  bool has_unstep() const { return kind == 3 || kind == 4; }

  // Memo keys must encode the LOGICAL state: the fifo ring buffer's
  // (head, tail) offsets and dead slots are representation, not state
  // — canonicalize to [live values at 0.., count, 0] so memo behavior
  // (and hence step counts) exactly matches the host search, which
  // memoizes on the model's items tuple.
  std::vector<int32_t> canon(const std::vector<int32_t>& state) const {
    if (kind != 4) return state;
    const int w = width - 2;
    const int32_t head = state[w], tail = state[w + 1];
    std::vector<int32_t> out(width, 0);
    for (int32_t i = head; i < tail; ++i) out[i - head] = state[i];
    out[w] = tail - head;
    return out;
  }
};

std::string make_key(const std::vector<uint64_t>& bits,
                     const std::vector<int32_t>& state,
                     bool state_in_key) {
  std::string out;
  out.reserve(bits.size() * 8 + (state_in_key ? state.size() * 4 : 0));
  out.append(reinterpret_cast<const char*>(bits.data()),
             bits.size() * sizeof(uint64_t));
  if (state_in_key) {
    out.append(reinterpret_cast<const char*>(state.data()),
               state.size() * sizeof(int32_t));
  }
  return out;
}

}  // namespace

extern "C" {

// Returns total search steps. out_valid: 0 false / 1 true / 2 unknown.
// out_best receives the deepest legal prefix (entry ids); caller
// provides a buffer of n ints. out_stuck is the entry at whose return
// the search died (-1 when not applicable).
long long wgl_search(int n, const int32_t* f, const int32_t* v1,
                     const int32_t* v2, const uint8_t* crashed,
                     const int64_t* call_pos, const int64_t* ret_pos,
                     int model_kind, int32_t init_state, int state_width,
                     long long max_steps, double time_limit_s,
                     int* out_valid, int* out_stuck, int* out_best,
                     int* out_best_len, long long* out_cache_size) {
  *out_valid = kUnknown;
  *out_stuck = -1;
  *out_best_len = 0;
  *out_cache_size = 0;

  int n_completed = 0;
  for (int e = 0; e < n; ++e) n_completed += crashed[e] ? 0 : 1;
  if (n_completed == 0) {
    *out_valid = kTrue;
    return 0;
  }

  Stepper stepper{model_kind, state_width};
  std::vector<int32_t> state(state_width, 0);
  if (model_kind == 3 || model_kind == 4) {
    std::fill(state.begin(), state.end(), 0);
  } else {
    state[0] = init_state;
  }

  // Event linked list: node id = event position + 1; 0 is the head
  // sentinel (and the off-the-end target).
  const int n_nodes = 2 * n + 1;
  std::vector<int> nxt(n_nodes), prv(n_nodes), node_entry(n_nodes, 0);
  std::vector<uint8_t> node_is_call(n_nodes, 0);
  std::vector<int> call_node(n), ret_node(n);
  for (int i = 0; i < n_nodes; ++i) {
    nxt[i] = i + 1;
    prv[i] = i - 1;
  }
  nxt[n_nodes - 1] = 0;
  prv[0] = 0;
  for (int e = 0; e < n; ++e) {
    int c = static_cast<int>(call_pos[e]) + 1;
    int r = static_cast<int>(ret_pos[e]) + 1;
    call_node[e] = c;
    ret_node[e] = r;
    node_entry[c] = e;
    node_entry[r] = e;
    node_is_call[c] = 1;
  }
  constexpr int kEnd = 0;

  auto lift = [&](int e) {
    for (int nd : {call_node[e], ret_node[e]}) {
      int p = prv[nd], q = nxt[nd];
      nxt[p] = q;
      if (q != kEnd) prv[q] = p;
    }
  };
  auto unlift = [&](int e) {
    for (int nd : {ret_node[e], call_node[e]}) {
      int p = prv[nd], q = nxt[nd];
      nxt[p] = nd;
      if (q != kEnd) prv[q] = nd;
    }
  };

  const int n_words = (n + 63) / 64;
  std::vector<uint64_t> lin(n_words, 0);

  struct Frame {
    int entry;
    int32_t prev_scalar;  // scalar models' state snapshot
  };
  std::vector<Frame> stack;
  stack.reserve(n);

  std::unordered_set<std::string> cache;
  // canon() copies; only the fifo kind needs canonicalization, every
  // other kind keeps the zero-copy path
  cache.insert(stepper.kind == 4
                   ? make_key(lin, stepper.canon(state), true)
                   : make_key(lin, state, stepper.state_in_key()));

  int completed_done = 0;
  int best_depth = -1;
  std::vector<int> best_entries;
  int stuck_entry = -1;

  int node = nxt[0];
  long long steps = 0;
  // computed only when a limit is set: casting a huge sentinel double
  // into the clock's int64 rep would be UB
  const bool has_deadline = time_limit_s >= 0;
  const auto deadline =
      std::chrono::steady_clock::now() +
      std::chrono::duration_cast<std::chrono::steady_clock::duration>(
          std::chrono::duration<double>(has_deadline ? time_limit_s
                                                     : 0.0));

  while (true) {
    ++steps;
    if (max_steps >= 0 && steps > max_steps) {
      *out_valid = kUnknown;
      *out_cache_size = static_cast<long long>(cache.size());
      return steps;
    }
    if (has_deadline && (steps & 4095) == 0 &&
        std::chrono::steady_clock::now() > deadline) {
      *out_valid = kUnknown;
      *out_cache_size = static_cast<long long>(cache.size());
      return steps;
    }

    if (node != kEnd && node_is_call[node]) {
      int e = node_entry[node];
      bool advanced = false;
      int32_t prev_scalar = state[0];
      std::vector<int32_t> saved;
      if (!stepper.has_unstep() && state_width > 1) saved = state;
      bool ok = stepper.step(state, f[e], v1[e], v2[e]);
      if (ok) {
        lin[e >> 6] |= (1ull << (e & 63));
        std::string key =
            stepper.kind == 4
                ? make_key(lin, stepper.canon(state), true)
                : make_key(lin, state, stepper.state_in_key());
        if (cache.insert(std::move(key)).second) {
          stack.push_back({e, prev_scalar});
          if (!crashed[e]) ++completed_done;
          lift(e);
          if (completed_done == n_completed) {
            *out_valid = kTrue;
            *out_best_len = static_cast<int>(stack.size());
            for (size_t i = 0; i < stack.size(); ++i)
              out_best[i] = stack[i].entry;
            *out_cache_size = static_cast<long long>(cache.size());
            return steps;
          }
          node = nxt[0];
          advanced = true;
        } else {
          // seen: undo the state mutation + bit
          lin[e >> 6] &= ~(1ull << (e & 63));
          if (stepper.has_unstep())
            stepper.unstep(state, f[e], v1[e]);
          else if (state_width > 1)
            state = saved;
          else
            state[0] = prev_scalar;
        }
      }
      if (!advanced) {
        if (!ok) {
          // step refused: restore scalar (queue step only mutates on ok)
          if (!stepper.has_unstep()) state[0] = prev_scalar;
        }
        node = nxt[node];
      }
    } else {
      // Return event (or end): nothing minimal linearizes here.
      if (static_cast<int>(stack.size()) > best_depth) {
        best_depth = static_cast<int>(stack.size());
        best_entries.clear();
        for (const Frame& fr : stack) best_entries.push_back(fr.entry);
        stuck_entry = (node != kEnd) ? node_entry[node] : -1;
      }
      if (stack.empty()) {
        *out_valid = kFalse;
        *out_stuck = stuck_entry;
        *out_best_len = static_cast<int>(best_entries.size());
        for (size_t i = 0; i < best_entries.size(); ++i)
          out_best[i] = best_entries[i];
        *out_cache_size = static_cast<long long>(cache.size());
        return steps;
      }
      Frame fr = stack.back();
      stack.pop_back();
      int e = fr.entry;
      lin[e >> 6] &= ~(1ull << (e & 63));
      if (stepper.has_unstep())
        stepper.unstep(state, f[e], v1[e]);
      else
        state[0] = fr.prev_scalar;
      if (!crashed[e]) --completed_done;
      unlift(e);
      node = nxt[call_node[e]];
    }
  }
}

}  // extern "C"
