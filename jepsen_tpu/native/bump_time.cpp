// bump-time: one-shot wall-clock jump by <delta> milliseconds.
// C++ port of the reference tool (jepsen/resources/bump-time.c:1-53),
// uploaded to nodes and compiled there by jepsen_tpu.nemesis.time
// (the analog of nemesis/time.clj:14-41).
//
// usage: bump-time [--dry-run] <delta-ms>
//   Adjusts the system wall clock by delta ms and prints the resulting
//   time as "seconds.microseconds". With --dry-run, computes and prints
//   the would-be time without calling settimeofday (for tests and
//   rootless sanity checks).

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <sys/time.h>

namespace {

// Normalize tv_usec into [0, 1e6) (bump-time.c:30-38)
void balance(timeval &t) {
  while (t.tv_usec < 0) {
    t.tv_sec -= 1;
    t.tv_usec += 1000000;
  }
  while (t.tv_usec >= 1000000) {
    t.tv_sec += 1;
    t.tv_usec -= 1000000;
  }
}

} // namespace

int main(int argc, char **argv) {
  bool dry_run = false;
  const char *delta_arg = nullptr;
  for (int i = 1; i < argc; i++) {
    if (std::strcmp(argv[i], "--dry-run") == 0 ||
        std::strcmp(argv[i], "-n") == 0) {
      dry_run = true;
    } else {
      delta_arg = argv[i];
    }
  }
  if (delta_arg == nullptr) {
    std::fprintf(stderr, "usage: %s [--dry-run] <delta>, where delta is in ms\n",
                 argv[0]);
    return 1;
  }

  const int64_t delta_us_total =
      static_cast<int64_t>(std::atof(delta_arg) * 1000.0);

  timeval now{};
  timezone tz{};
  if (gettimeofday(&now, &tz) != 0) {
    std::perror("gettimeofday");
    return 1;
  }

  now.tv_usec += delta_us_total % 1000000;
  now.tv_sec += delta_us_total / 1000000;
  balance(now);

  if (!dry_run) {
    if (settimeofday(&now, &tz) != 0) {
      std::perror("settimeofday");
      return 2;
    }
    if (gettimeofday(&now, &tz) != 0) {
      std::perror("gettimeofday");
      return 1;
    }
  }

  std::printf("%lld.%06lld\n", static_cast<long long>(now.tv_sec),
              static_cast<long long>(now.tv_usec));
  return 0;
}
