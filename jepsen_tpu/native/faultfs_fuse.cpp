// faultfs_fuse — process-agnostic filesystem fault injection: a FUSE
// passthrough filesystem speaking the RAW kernel protocol over
// /dev/fuse (no libfuse, no thrift), with an EIO fault switch driven
// by the same control file as the LD_PRELOAD interposer (faultfs.cpp).
//
// This is the TPU-era equivalent of the reference's charybdefs
// (charybdefs/src/jepsen/charybdefs.clj:40-85 builds a thrift-driven
// FUSE C++ filesystem on each node and mounts it over the data dir;
// :72-85 are break-all / break-one-percent / clear). Where the
// LD_PRELOAD interposer is a no-op for statically linked executables
// (etcd, consul, cockroach — most Go binaries), a FUSE mount faults
// ANY process's I/O, because the fault lives below the VFS boundary.
//
//   faultfs_fuse <backing_dir> <mountpoint> <ctl_file> [--foreground]
//
// Control file (re-read at most every 100 ms; same grammar as
// faultfs.cpp): first line `off` | `all` | `percent <n>`. "all" fails
// every faultable operation with EIO; "percent n" fails ~n% of them;
// "off" passes everything through. Operations the kernel needs for
// its own bookkeeping (INIT/FORGET/RELEASE/DESTROY/INTERRUPT) are
// never faulted — breaking those leaks kernel references instead of
// simulating a broken disk.
//
// Implementation notes:
// - The protocol structs come from the kernel uapi <linux/fuse.h>;
//   we negotiate down to the header's minor version in INIT and the
//   kernel handles compatibility.
// - Files open with FOPEN_DIRECT_IO so every read/write round-trips
//   to the daemon — an EIO storm must not be absorbed by the page
//   cache (the DB's own caching is above us and unaffected).
// - Inodes: nodeid -> O_PATH fd, deduped by (st_dev, st_ino) with
//   nlookup refcounts (FORGET closes at zero). I/O fds reopen via
//   /proc/self/fd — the standard passthrough trick.
// - Readdir snapshots the directory at offset 0 and serves by index,
//   sidestepping telldir cookie semantics.

#include <linux/fuse.h>

#include <dirent.h>
#include <errno.h>
#include <fcntl.h>
#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/mount.h>
#include <sys/stat.h>
#include <sys/statfs.h>
#include <sys/time.h>
#include <sys/types.h>
#include <time.h>
#include <unistd.h>

#include <cstdint>
#include <map>
#include <string>
#include <utility>
#include <vector>

namespace {

constexpr size_t kBufSize = 1 << 20;  // >= max_write + headers
constexpr uint32_t kMaxWrite = 128 * 1024;

// ---------------------------------------------------------------- ctl
struct Ctl {
  std::string path;
  int mode = 0;  // 0 off, 1 all, 2 percent
  int pct = 0;
  uint32_t rng = 0x9E3779B9u;
  struct timespec last = {0, 0};

  void refresh() {
    struct timespec now;
    clock_gettime(CLOCK_MONOTONIC, &now);
    long ms = (now.tv_sec - last.tv_sec) * 1000 +
              (now.tv_nsec - last.tv_nsec) / 1000000;
    if (last.tv_sec != 0 && ms >= 0 && ms < 100) return;
    last = now;
    int fd = open(path.c_str(), O_RDONLY);
    if (fd < 0) {
      mode = 0;
      return;
    }
    char buf[128];
    ssize_t n = read(fd, buf, sizeof buf - 1);
    close(fd);
    if (n <= 0) {
      mode = 0;
      return;
    }
    buf[n] = 0;
    char word[32];
    int p = 0;
    if (sscanf(buf, "%31s %d", word, &p) < 1) {
      mode = 0;
    } else if (strcmp(word, "all") == 0) {
      mode = 1;
    } else if (strcmp(word, "percent") == 0) {
      mode = 2;
      pct = p < 0 ? 0 : (p > 100 ? 100 : p);
    } else {
      mode = 0;
    }
  }

  bool fault() {
    refresh();
    if (mode == 1) return true;
    if (mode != 2) return false;
    rng = rng * 1664525u + 1013904223u;
    return (int)((rng >> 16) % 100u) < pct;
  }
};

// ------------------------------------------------------------- inodes
struct Inode {
  int path_fd = -1;  // O_PATH handle
  uint64_t nlookup = 0;
  dev_t dev = 0;
  ino_t ino = 0;
};

struct DirSnapshot {
  int fd = -1;  // backing dir fd (owned)
  struct Ent {
    std::string name;
    uint64_t ino;
    uint32_t type;
  };
  std::vector<Ent> ents;
  bool loaded = false;
};

struct Fs {
  std::map<uint64_t, Inode> inodes;                 // nodeid -> inode
  std::map<std::pair<dev_t, ino_t>, uint64_t> ids;  // (dev,ino) -> nodeid
  std::map<uint64_t, DirSnapshot*> dirs;            // fh -> snapshot
  uint64_t next_id = 2;  // 1 is the root
  Ctl ctl;

  int fd_of(uint64_t nodeid) {
    auto it = inodes.find(nodeid);
    return it == inodes.end() ? -1 : it->second.path_fd;
  }
};

Fs fs;

void attr_from_stat(const struct stat& st, fuse_attr* a) {
  memset(a, 0, sizeof *a);
  a->ino = st.st_ino;
  a->size = st.st_size;
  a->blocks = st.st_blocks;
  a->atime = st.st_atim.tv_sec;
  a->mtime = st.st_mtim.tv_sec;
  a->ctime = st.st_ctim.tv_sec;
  a->atimensec = st.st_atim.tv_nsec;
  a->mtimensec = st.st_mtim.tv_nsec;
  a->ctimensec = st.st_ctim.tv_nsec;
  a->mode = st.st_mode;
  a->nlink = st.st_nlink;
  a->uid = st.st_uid;
  a->gid = st.st_gid;
  a->rdev = st.st_rdev;
  a->blksize = st.st_blksize;
}

// register/lookup an inode for a child; bumps nlookup
int make_entry(int parent_fd, const char* name, fuse_entry_out* out) {
  int pfd = openat(parent_fd, name, O_PATH | O_NOFOLLOW);
  if (pfd < 0) return -errno;
  struct stat st;
  if (fstatat(pfd, "", &st, AT_EMPTY_PATH | AT_SYMLINK_NOFOLLOW) < 0) {
    int e = errno;
    close(pfd);
    return -e;
  }
  auto key = std::make_pair(st.st_dev, st.st_ino);
  uint64_t id;
  auto it = fs.ids.find(key);
  if (it != fs.ids.end()) {
    id = it->second;
    fs.inodes[id].nlookup++;
    close(pfd);
  } else {
    id = fs.next_id++;
    fs.ids[key] = id;
    Inode ino;
    ino.path_fd = pfd;
    ino.nlookup = 1;
    ino.dev = st.st_dev;
    ino.ino = st.st_ino;
    fs.inodes[id] = ino;
  }
  memset(out, 0, sizeof *out);
  out->nodeid = id;
  out->attr_valid = 1;
  out->entry_valid = 1;
  attr_from_stat(st, &out->attr);
  return 0;
}

void forget_one(uint64_t nodeid, uint64_t n) {
  auto it = fs.inodes.find(nodeid);
  if (it == fs.inodes.end() || nodeid == FUSE_ROOT_ID) return;
  if (it->second.nlookup <= n) {
    fs.ids.erase(std::make_pair(it->second.dev, it->second.ino));
    close(it->second.path_fd);
    fs.inodes.erase(it);
  } else {
    it->second.nlookup -= n;
  }
}

int reopen(int path_fd, int flags) {
  char p[64];
  snprintf(p, sizeof p, "/proc/self/fd/%d", path_fd);
  return open(p, flags);
}

// ------------------------------------------------------------ replies
int dev_fd = -1;

void send_reply(uint64_t unique, int error, const void* data, size_t size) {
  fuse_out_header h;
  h.len = (uint32_t)(sizeof h + size);
  h.error = error;
  h.unique = unique;
  struct iovec {
    const void* base;
    size_t len;
  };
  char out[kBufSize];
  memcpy(out, &h, sizeof h);
  if (size) memcpy(out + sizeof h, data, size);
  ssize_t r = write(dev_fd, out, sizeof h + size);
  (void)r;  // ENOENT from a raced INTERRUPT is fine
}

void reply_err(uint64_t unique, int negerrno) {
  send_reply(unique, negerrno, nullptr, 0);
}

// faultable-op gate: one check per request
bool faulted(uint64_t unique) {
  if (!fs.ctl.fault()) return false;
  reply_err(unique, -EIO);
  return true;
}

bool setup_root(const char* backing) {
  int fd = open(backing, O_PATH | O_DIRECTORY);
  if (fd < 0) return false;
  struct stat st;
  fstat(fd, &st);
  Inode root;
  root.path_fd = fd;
  root.nlookup = 1;
  root.dev = st.st_dev;
  root.ino = st.st_ino;
  fs.inodes[FUSE_ROOT_ID] = root;
  fs.ids[std::make_pair(st.st_dev, st.st_ino)] = FUSE_ROOT_ID;
  return true;
}

volatile sig_atomic_t stop_flag = 0;
void on_term(int) { stop_flag = 1; }

}  // namespace

int main(int argc, char** argv) {
  if (argc < 4) {
    fprintf(stderr,
            "usage: %s <backing_dir> <mountpoint> <ctl_file> "
            "[--foreground]\n",
            argv[0]);
    return 2;
  }
  const char* backing = argv[1];
  const char* mnt = argv[2];
  fs.ctl.path = argv[3];
  bool foreground = argc > 4 && strcmp(argv[4], "--foreground") == 0;

  if (!setup_root(backing)) {
    perror("backing dir");
    return 2;
  }
  dev_fd = open("/dev/fuse", O_RDWR);
  if (dev_fd < 0) {
    perror("/dev/fuse");
    return 2;
  }
  char opts[256];
  snprintf(opts, sizeof opts,
           "fd=%d,rootmode=40000,user_id=0,group_id=0,allow_other,"
           "default_permissions",
           dev_fd);
  if (mount("faultfs", mnt, "fuse.faultfs", MS_NOSUID | MS_NODEV, opts)) {
    perror("mount");
    return 2;
  }
  if (!foreground) {
    if (fork() > 0) return 0;  // parent: mount is live
    setsid();
    // detach stdio: the daemon inherits the launcher's pipes, and a
    // captured exec would otherwise block on EOF forever
    int devnull = open("/dev/null", O_RDWR);
    dup2(devnull, 0);
    dup2(devnull, 1);
    dup2(devnull, 2);
    if (devnull > 2) close(devnull);
  }
  // sigaction WITHOUT SA_RESTART: the main loop blocks in
  // read(dev_fd), and glibc's signal() would transparently restart it
  // so an idle daemon never observes stop_flag — EINTR must surface.
  struct sigaction sa;
  memset(&sa, 0, sizeof sa);
  sa.sa_handler = on_term;
  sigaction(SIGTERM, &sa, nullptr);
  sigaction(SIGINT, &sa, nullptr);

  std::vector<char> buf(kBufSize);
  while (!stop_flag) {
    ssize_t n = read(dev_fd, buf.data(), buf.size());
    if (n < 0) {
      if (errno == EINTR || errno == EAGAIN) continue;
      break;  // ENODEV: unmounted
    }
    if ((size_t)n < sizeof(fuse_in_header)) continue;
    auto* in = (fuse_in_header*)buf.data();
    char* arg = buf.data() + sizeof(fuse_in_header);

    switch (in->opcode) {
      case FUSE_INIT: {
        auto* ii = (fuse_init_in*)arg;
        fuse_init_out out;
        memset(&out, 0, sizeof out);
        out.major = FUSE_KERNEL_VERSION;
        out.minor = FUSE_KERNEL_MINOR_VERSION < ii->minor
                        ? FUSE_KERNEL_MINOR_VERSION
                        : ii->minor;
        out.max_readahead = ii->max_readahead;
        out.flags = 0;
        out.max_background = 16;
        out.congestion_threshold = 12;
        out.max_write = kMaxWrite;
        out.time_gran = 1;
        send_reply(in->unique, 0, &out, sizeof out);
        break;
      }
      case FUSE_DESTROY:
        send_reply(in->unique, 0, nullptr, 0);
        stop_flag = 1;
        break;
      case FUSE_FORGET: {
        auto* f = (fuse_forget_in*)arg;
        forget_one(in->nodeid, f->nlookup);
        break;  // no reply
      }
      case FUSE_BATCH_FORGET: {
        auto* bf = (fuse_batch_forget_in*)arg;
        auto* items = (fuse_forget_one*)(arg + sizeof *bf);
        for (uint32_t i = 0; i < bf->count; i++)
          forget_one(items[i].nodeid, items[i].nlookup);
        break;  // no reply
      }
      case FUSE_INTERRUPT:
        break;  // best-effort: we never block anyway
      case FUSE_LOOKUP: {
        if (faulted(in->unique)) break;
        fuse_entry_out out;
        int e = make_entry(fs.fd_of(in->nodeid), arg, &out);
        if (e)
          reply_err(in->unique, e);
        else
          send_reply(in->unique, 0, &out, sizeof out);
        break;
      }
      case FUSE_GETATTR: {
        if (faulted(in->unique)) break;
        struct stat st;
        int r = fstatat(fs.fd_of(in->nodeid), "", &st,
                        AT_EMPTY_PATH | AT_SYMLINK_NOFOLLOW);
        if (r < 0) {
          reply_err(in->unique, -errno);
          break;
        }
        fuse_attr_out out;
        memset(&out, 0, sizeof out);
        out.attr_valid = 1;
        attr_from_stat(st, &out.attr);
        send_reply(in->unique, 0, &out, sizeof out);
        break;
      }
      case FUSE_SETATTR: {
        if (faulted(in->unique)) break;
        auto* s = (fuse_setattr_in*)arg;
        int pfd = fs.fd_of(in->nodeid);
        int e = 0;
        int rw = -1;  // lazily opened read-write fd for truncate
        if (!e && (s->valid & FATTR_MODE))
          if (fchmod(rw = (rw >= 0 ? rw : reopen(pfd, O_RDONLY)),
                     s->mode) < 0)
            e = -errno;
        if (!e && (s->valid & (FATTR_UID | FATTR_GID))) {
          uid_t u = (s->valid & FATTR_UID) ? s->uid : (uid_t)-1;
          gid_t g = (s->valid & FATTR_GID) ? s->gid : (gid_t)-1;
          char p[64];
          snprintf(p, sizeof p, "/proc/self/fd/%d", pfd);
          if (chown(p, u, g) < 0) e = -errno;
        }
        if (!e && (s->valid & FATTR_SIZE)) {
          int tfd = (s->valid & FATTR_FH) ? (int)s->fh
                                          : reopen(pfd, O_WRONLY);
          if (tfd < 0 || ftruncate(tfd, s->size) < 0) e = -errno;
          if (!(s->valid & FATTR_FH) && tfd >= 0) close(tfd);
        }
        if (!e && (s->valid & (FATTR_ATIME | FATTR_MTIME))) {
          struct timespec ts[2];
          ts[0].tv_nsec = UTIME_OMIT;
          ts[1].tv_nsec = UTIME_OMIT;
          if (s->valid & FATTR_ATIME) {
            ts[0].tv_sec = s->atime;
            ts[0].tv_nsec = (s->valid & FATTR_ATIME_NOW) ? UTIME_NOW
                                                         : s->atimensec;
          }
          if (s->valid & FATTR_MTIME) {
            ts[1].tv_sec = s->mtime;
            ts[1].tv_nsec = (s->valid & FATTR_MTIME_NOW) ? UTIME_NOW
                                                         : s->mtimensec;
          }
          char p[64];
          snprintf(p, sizeof p, "/proc/self/fd/%d", pfd);
          if (utimensat(AT_FDCWD, p, ts, 0) < 0) e = -errno;
        }
        if (rw >= 0) close(rw);
        if (e) {
          reply_err(in->unique, e);
          break;
        }
        struct stat st;
        fstatat(pfd, "", &st, AT_EMPTY_PATH | AT_SYMLINK_NOFOLLOW);
        fuse_attr_out out;
        memset(&out, 0, sizeof out);
        out.attr_valid = 1;
        attr_from_stat(st, &out.attr);
        send_reply(in->unique, 0, &out, sizeof out);
        break;
      }
      case FUSE_READLINK: {
        if (faulted(in->unique)) break;
        char target[4096];
        ssize_t r = readlinkat(fs.fd_of(in->nodeid), "", target,
                               sizeof target - 1);
        if (r < 0)
          reply_err(in->unique, -errno);
        else
          send_reply(in->unique, 0, target, r);
        break;
      }
      case FUSE_MKDIR: {
        if (faulted(in->unique)) break;
        auto* m = (fuse_mkdir_in*)arg;
        const char* name = arg + sizeof *m;
        if (mkdirat(fs.fd_of(in->nodeid), name, m->mode) < 0) {
          reply_err(in->unique, -errno);
          break;
        }
        fuse_entry_out out;
        int e = make_entry(fs.fd_of(in->nodeid), name, &out);
        if (e)
          reply_err(in->unique, e);
        else
          send_reply(in->unique, 0, &out, sizeof out);
        break;
      }
      case FUSE_MKNOD: {
        if (faulted(in->unique)) break;
        auto* m = (fuse_mknod_in*)arg;
        const char* name = arg + sizeof *m;
        if (mknodat(fs.fd_of(in->nodeid), name, m->mode, m->rdev) < 0) {
          reply_err(in->unique, -errno);
          break;
        }
        fuse_entry_out out;
        int e = make_entry(fs.fd_of(in->nodeid), name, &out);
        if (e)
          reply_err(in->unique, e);
        else
          send_reply(in->unique, 0, &out, sizeof out);
        break;
      }
      case FUSE_SYMLINK: {
        if (faulted(in->unique)) break;
        const char* name = arg;
        const char* target = arg + strlen(name) + 1;
        if (symlinkat(target, fs.fd_of(in->nodeid), name) < 0) {
          reply_err(in->unique, -errno);
          break;
        }
        fuse_entry_out out;
        int e = make_entry(fs.fd_of(in->nodeid), name, &out);
        if (e)
          reply_err(in->unique, e);
        else
          send_reply(in->unique, 0, &out, sizeof out);
        break;
      }
      case FUSE_LINK: {
        if (faulted(in->unique)) break;
        auto* l = (fuse_link_in*)arg;
        const char* name = arg + sizeof *l;
        char p[64];
        snprintf(p, sizeof p, "/proc/self/fd/%d",
                 fs.fd_of(l->oldnodeid));
        if (linkat(AT_FDCWD, p, fs.fd_of(in->nodeid), name,
                   AT_SYMLINK_FOLLOW) < 0) {
          reply_err(in->unique, -errno);
          break;
        }
        fuse_entry_out out;
        int e = make_entry(fs.fd_of(in->nodeid), name, &out);
        if (e)
          reply_err(in->unique, e);
        else
          send_reply(in->unique, 0, &out, sizeof out);
        break;
      }
      case FUSE_UNLINK: {
        if (faulted(in->unique)) break;
        reply_err(in->unique,
                  unlinkat(fs.fd_of(in->nodeid), arg, 0) < 0 ? -errno : 0);
        break;
      }
      case FUSE_RMDIR: {
        if (faulted(in->unique)) break;
        reply_err(in->unique,
                  unlinkat(fs.fd_of(in->nodeid), arg, AT_REMOVEDIR) < 0
                      ? -errno
                      : 0);
        break;
      }
      case FUSE_RENAME:
      case FUSE_RENAME2: {
        if (faulted(in->unique)) break;
        uint64_t newdir;
        const char* oldname;
        if (in->opcode == FUSE_RENAME2) {
          auto* r = (fuse_rename2_in*)arg;
          newdir = r->newdir;
          oldname = arg + sizeof *r;
        } else {
          auto* r = (fuse_rename_in*)arg;
          newdir = r->newdir;
          oldname = arg + sizeof(fuse_rename_in);
        }
        const char* newname = oldname + strlen(oldname) + 1;
        reply_err(in->unique,
                  renameat(fs.fd_of(in->nodeid), oldname,
                           fs.fd_of(newdir), newname) < 0
                      ? -errno
                      : 0);
        break;
      }
      case FUSE_OPEN: {
        if (faulted(in->unique)) break;
        auto* o = (fuse_open_in*)arg;
        int f = reopen(fs.fd_of(in->nodeid),
                       o->flags & ~(O_NOFOLLOW | O_CREAT));
        if (f < 0) {
          reply_err(in->unique, -errno);
          break;
        }
        fuse_open_out out;
        memset(&out, 0, sizeof out);
        out.fh = f;
        out.open_flags = FOPEN_DIRECT_IO;  // every I/O hits the daemon
        send_reply(in->unique, 0, &out, sizeof out);
        break;
      }
      case FUSE_CREATE: {
        if (faulted(in->unique)) break;
        auto* c = (fuse_create_in*)arg;
        const char* name = arg + sizeof *c;
        int f = openat(fs.fd_of(in->nodeid), name,
                       (c->flags | O_CREAT) & ~O_NOFOLLOW, c->mode);
        if (f < 0) {
          reply_err(in->unique, -errno);
          break;
        }
        struct {
          fuse_entry_out e;
          fuse_open_out o;
        } out;
        int e = make_entry(fs.fd_of(in->nodeid), name, &out.e);
        if (e) {
          close(f);
          reply_err(in->unique, e);
          break;
        }
        memset(&out.o, 0, sizeof out.o);
        out.o.fh = f;
        out.o.open_flags = FOPEN_DIRECT_IO;
        send_reply(in->unique, 0, &out, sizeof out);
        break;
      }
      case FUSE_READ: {
        if (faulted(in->unique)) break;
        auto* r = (fuse_read_in*)arg;
        std::vector<char> data(r->size);
        ssize_t got = pread((int)r->fh, data.data(), r->size, r->offset);
        if (got < 0)
          reply_err(in->unique, -errno);
        else
          send_reply(in->unique, 0, data.data(), got);
        break;
      }
      case FUSE_WRITE: {
        if (faulted(in->unique)) break;
        auto* w = (fuse_write_in*)arg;
        const char* data = arg + sizeof *w;
        ssize_t put = pwrite((int)w->fh, data, w->size, w->offset);
        if (put < 0) {
          reply_err(in->unique, -errno);
          break;
        }
        fuse_write_out out;
        memset(&out, 0, sizeof out);
        out.size = (uint32_t)put;
        send_reply(in->unique, 0, &out, sizeof out);
        break;
      }
      case FUSE_FLUSH:
        send_reply(in->unique, 0, nullptr, 0);
        break;
      case FUSE_RELEASE: {
        auto* rl = (fuse_release_in*)arg;
        close((int)rl->fh);
        send_reply(in->unique, 0, nullptr, 0);
        break;
      }
      case FUSE_FSYNC:
      case FUSE_FSYNCDIR: {
        if (faulted(in->unique)) break;
        auto* fy = (fuse_fsync_in*)arg;
        int fd = (int)fy->fh;
        if (in->opcode == FUSE_FSYNCDIR) {
          auto it = fs.dirs.find(fy->fh);
          fd = it == fs.dirs.end() ? -1 : it->second->fd;
        }
        int r = (fy->fsync_flags & 1) ? fdatasync(fd) : fsync(fd);
        reply_err(in->unique, r < 0 ? -errno : 0);
        break;
      }
      case FUSE_OPENDIR: {
        if (faulted(in->unique)) break;
        int f = reopen(fs.fd_of(in->nodeid), O_RDONLY | O_DIRECTORY);
        if (f < 0) {
          reply_err(in->unique, -errno);
          break;
        }
        auto* snap = new DirSnapshot();
        snap->fd = f;
        fuse_open_out out;
        memset(&out, 0, sizeof out);
        out.fh = (uint64_t)(uintptr_t)snap;
        fs.dirs[out.fh] = snap;
        send_reply(in->unique, 0, &out, sizeof out);
        break;
      }
      case FUSE_READDIR: {
        if (faulted(in->unique)) break;
        auto* r = (fuse_read_in*)arg;
        auto it = fs.dirs.find(r->fh);
        if (it == fs.dirs.end()) {
          reply_err(in->unique, -EBADF);
          break;
        }
        DirSnapshot* snap = it->second;
        if (r->offset == 0 || !snap->loaded) {
          snap->ents.clear();
          DIR* d = fdopendir(dup(snap->fd));
          if (d) {
            rewinddir(d);
            while (struct dirent* de = readdir(d))
              snap->ents.push_back(
                  {de->d_name, de->d_ino, (uint32_t)de->d_type});
            closedir(d);
          }
          snap->loaded = true;
        }
        std::vector<char> out;
        size_t idx = (size_t)r->offset;
        while (idx < snap->ents.size()) {
          const auto& e = snap->ents[idx];
          size_t entlen = FUSE_NAME_OFFSET + e.name.size();
          size_t padded = FUSE_DIRENT_ALIGN(entlen);
          if (out.size() + padded > r->size) break;
          size_t base = out.size();
          out.resize(base + padded, 0);
          auto* de = (fuse_dirent*)(out.data() + base);
          de->ino = e.ino;
          de->off = idx + 1;  // cookie: next index
          de->namelen = (uint32_t)e.name.size();
          de->type = e.type;
          memcpy(de->name, e.name.data(), e.name.size());
          idx++;
        }
        send_reply(in->unique, 0, out.data(), out.size());
        break;
      }
      case FUSE_RELEASEDIR: {
        auto* rl = (fuse_release_in*)arg;
        auto it = fs.dirs.find(rl->fh);
        if (it != fs.dirs.end()) {
          close(it->second->fd);
          delete it->second;
          fs.dirs.erase(it);
        }
        send_reply(in->unique, 0, nullptr, 0);
        break;
      }
      case FUSE_STATFS: {
        if (faulted(in->unique)) break;
        struct statfs st;
        char p[64];
        snprintf(p, sizeof p, "/proc/self/fd/%d", fs.fd_of(in->nodeid));
        if (statfs(p, &st) < 0) {
          reply_err(in->unique, -errno);
          break;
        }
        fuse_statfs_out out;
        memset(&out, 0, sizeof out);
        out.st.blocks = st.f_blocks;
        out.st.bfree = st.f_bfree;
        out.st.bavail = st.f_bavail;
        out.st.files = st.f_files;
        out.st.ffree = st.f_ffree;
        out.st.bsize = st.f_bsize;
        out.st.namelen = st.f_namelen;
        out.st.frsize = st.f_frsize;
        send_reply(in->unique, 0, &out, sizeof out);
        break;
      }
      case FUSE_ACCESS: {
        if (faulted(in->unique)) break;
        auto* a = (fuse_access_in*)arg;
        char p[64];
        snprintf(p, sizeof p, "/proc/self/fd/%d", fs.fd_of(in->nodeid));
        reply_err(in->unique, access(p, a->mask) < 0 ? -errno : 0);
        break;
      }
      case FUSE_FALLOCATE: {
        if (faulted(in->unique)) break;
        auto* fa = (fuse_fallocate_in*)arg;
        reply_err(in->unique,
                  fallocate((int)fa->fh, fa->mode, fa->offset,
                            fa->length) < 0
                      ? -errno
                      : 0);
        break;
      }
      case FUSE_GETXATTR:
      case FUSE_SETXATTR:
      case FUSE_LISTXATTR:
      case FUSE_REMOVEXATTR:
      case FUSE_GETLK:
      case FUSE_SETLK:
      case FUSE_SETLKW:
      default:
        reply_err(in->unique, -ENOSYS);
        break;
    }
  }
  umount2(mnt, MNT_DETACH);
  return 0;
}
