// faultfs: filesystem fault injection via LD_PRELOAD interposition.
//
// TPU-era equivalent of the charybdefs FUSE layer the reference drives
// (/root/reference/charybdefs/src/jepsen/charybdefs.clj:40-85 — studied
// for behavior, not copied; charybdefs mounts a thrift-controlled FUSE
// passthrough at /faulty, this interposes libc I/O in the DB process
// itself, which needs no kernel module, no mount point, and no thrift).
//
// Control protocol: a small text file (FAULTFS_CTL env var, default
// /tmp/faultfs.ctl) re-read at most every 100 ms:
//     line 1:  off | all | percent <n>
//     line 2:  path prefix to affect (optional; default: everything)
// "all" fails every intercepted call with EIO (charybdefs break-all);
// "percent 1" fails ~1% of calls (break-one-percent); "off" is clear.
//
// Interposed: open/open64/openat/creat (fault at open + fd tracking),
// read/write/pread/pwrite/pread64/pwrite64/fsync/fdatasync on tracked
// fds, close (untrack). Faults are scoped to the path prefix so only
// the system under test's data directory misbehaves.
//
// Build:  g++ -shared -fPIC -O2 -o libfaultfs.so faultfs.cpp -ldl
// Use:    LD_PRELOAD=/path/libfaultfs.so FAULTFS_CTL=/path/ctl db-binary

#include <dlfcn.h>
#include <errno.h>
#include <fcntl.h>
#include <pthread.h>
#include <stdarg.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>
#include <unistd.h>

namespace {

enum Mode { MODE_OFF = 0, MODE_ALL = 1, MODE_PERCENT = 2 };

constexpr int kMaxFds = 65536;
constexpr long kRefreshNs = 100L * 1000 * 1000;  // 100 ms

long now_ns() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec * 1000000000L + ts.tv_nsec;
}

struct State {
  pthread_mutex_t mu = PTHREAD_MUTEX_INITIALIZER;
  Mode mode = MODE_OFF;
  int pct = 0;
  char prefix[4096] = {0};
  long last_refresh_ns = -1;
  unsigned rng;
  bool tracked[kMaxFds] = {false};

  State() {
    // per-process seed — a fixed constant would make every freshly
    // exec'd DB process roll the identical fault sequence. Seeding in
    // the constructor rides C++11's thread-safe function-local static
    // initialization (no racy lazy flag).
    unsigned seed = (unsigned)getpid() ^ (unsigned)now_ns();
    rng = seed ? seed : 0x2545F491u;
  }
};

State *state() {
  static State s;
  return &s;
}

const char *ctl_path() {
  const char *p = getenv("FAULTFS_CTL");
  return p && *p ? p : "/tmp/faultfs.ctl";
}

// Must use the real open/read to load the control file, or we'd
// recurse into our own interposers.
typedef int (*open_fn)(const char *, int, ...);
typedef ssize_t (*read_fn)(int, void *, size_t);
typedef int (*close_fn)(int);

template <typename T>
T real(const char *name) {
  static_assert(sizeof(T) == sizeof(void *), "fn ptr size");
  void *p = dlsym(RTLD_NEXT, name);
  T out;
  memcpy(&out, &p, sizeof(out));
  return out;
}

void refresh_locked(State *s) {
  long t = now_ns();
  if (s->last_refresh_ns >= 0 && t - s->last_refresh_ns < kRefreshNs) return;
  s->last_refresh_ns = t;
  static open_fn ropen = real<open_fn>("open");
  static read_fn rread = real<read_fn>("read");
  static close_fn rclose = real<close_fn>("close");
  int fd = ropen(ctl_path(), O_RDONLY);
  if (fd < 0) {
    s->mode = MODE_OFF;
    return;
  }
  char buf[8192];
  ssize_t n = rread(fd, buf, sizeof(buf) - 1);
  rclose(fd);
  if (n <= 0) {
    s->mode = MODE_OFF;
    return;
  }
  buf[n] = 0;
  char mode_word[32] = {0};
  int pct = 0;
  char pfx[4096] = {0};
  char *nl = strchr(buf, '\n');
  if (nl) {
    *nl = 0;
    char *p2 = nl + 1;
    char *nl2 = strchr(p2, '\n');
    if (nl2) *nl2 = 0;
    strncpy(pfx, p2, sizeof(pfx) - 1);
  }
  if (sscanf(buf, "%31s %d", mode_word, &pct) < 1) {
    s->mode = MODE_OFF;
    return;
  }
  if (strcmp(mode_word, "all") == 0) {
    s->mode = MODE_ALL;
  } else if (strcmp(mode_word, "percent") == 0) {
    s->mode = MODE_PERCENT;
    s->pct = pct < 0 ? 0 : (pct > 100 ? 100 : pct);
  } else {
    s->mode = MODE_OFF;
  }
  strncpy(s->prefix, pfx, sizeof(s->prefix) - 1);
}

bool path_in_scope_locked(State *s, const char *path) {
  if (!s->prefix[0]) return true;
  return path && strncmp(path, s->prefix, strlen(s->prefix)) == 0;
}

// xorshift — cheap, no libc rand() state contention
bool roll_locked(State *s) {
  s->rng ^= s->rng << 13;
  s->rng ^= s->rng >> 17;
  s->rng ^= s->rng << 5;
  return (int)(s->rng % 100u) < s->pct;
}

// Decide a fault for an op on `path` (open-style; also tracks fd intent).
bool fault_for_path(const char *path, bool *in_scope) {
  State *s = state();
  pthread_mutex_lock(&s->mu);
  refresh_locked(s);
  bool scope = path_in_scope_locked(s, path);
  bool fault = false;
  if (scope) {
    if (s->mode == MODE_ALL)
      fault = true;
    else if (s->mode == MODE_PERCENT)
      fault = roll_locked(s);
  }
  pthread_mutex_unlock(&s->mu);
  if (in_scope) *in_scope = scope;
  return fault;
}

// Decide a fault for an op on a tracked fd. The untracked case — every
// socket, pipe, and out-of-scope file in the process — must not pay for
// the mutex or control-file refresh, or the interposer would serialize
// the DB's whole I/O hot path and distort the concurrency under test:
// a racy unlocked peek at tracked[] is safe because entries only flip
// on open/close of that same fd (which the caller orders anyway).
bool fault_for_fd(int fd) {
  if (fd < 0 || fd >= kMaxFds) return false;
  State *s = state();
  if (!__atomic_load_n(&s->tracked[fd], __ATOMIC_ACQUIRE)) return false;
  pthread_mutex_lock(&s->mu);
  refresh_locked(s);
  bool fault = false;
  if (s->tracked[fd]) {
    if (s->mode == MODE_ALL)
      fault = true;
    else if (s->mode == MODE_PERCENT)
      fault = roll_locked(s);
  }
  pthread_mutex_unlock(&s->mu);
  return fault;
}

void track_fd(int fd, bool on) {
  if (fd < 0 || fd >= kMaxFds) return;
  State *s = state();
  // cold path (open/close): keep the mutex so untracking an fd
  // happens-before any other thread's use of a recycled fd number —
  // a plain relaxed store could leak a stale 'tracked' into an
  // innocent socket that reuses the fd
  pthread_mutex_lock(&s->mu);
  __atomic_store_n(&s->tracked[fd], on, __ATOMIC_RELEASE);
  pthread_mutex_unlock(&s->mu);
}

}  // namespace

extern "C" {

int open(const char *path, int flags, ...) {
  mode_t mode = 0;
  if (flags & O_CREAT) {
    va_list ap;
    va_start(ap, flags);
    mode = va_arg(ap, mode_t);
    va_end(ap);
  }
  bool in_scope = false;
  if (fault_for_path(path, &in_scope)) {
    errno = EIO;
    return -1;
  }
  static open_fn ropen = real<open_fn>("open");
  int fd = ropen(path, flags, mode);
  if (fd >= 0 && in_scope) track_fd(fd, true);
  return fd;
}

int open64(const char *path, int flags, ...) {
  mode_t mode = 0;
  if (flags & O_CREAT) {
    va_list ap;
    va_start(ap, flags);
    mode = va_arg(ap, mode_t);
    va_end(ap);
  }
  bool in_scope = false;
  if (fault_for_path(path, &in_scope)) {
    errno = EIO;
    return -1;
  }
  static open_fn ropen = real<open_fn>("open64");
  int fd = ropen(path, flags, mode);
  if (fd >= 0 && in_scope) track_fd(fd, true);
  return fd;
}

int openat(int dirfd, const char *path, int flags, ...) {
  mode_t mode = 0;
  if (flags & O_CREAT) {
    va_list ap;
    va_start(ap, flags);
    mode = va_arg(ap, mode_t);
    va_end(ap);
  }
  // Prefix scoping applies to absolute paths; AT_FDCWD-relative paths
  // are resolved against cwd for matching.
  char resolved[8192];
  const char *match = path;
  if (path && path[0] != '/' && dirfd == AT_FDCWD &&
      strlen(path) + 2 < sizeof(resolved)) {
    if (getcwd(resolved, sizeof(resolved) - strlen(path) - 2)) {
      size_t len = strlen(resolved);
      resolved[len] = '/';
      strcpy(resolved + len + 1, path);
      match = resolved;
    }
  }
  bool in_scope = false;
  if (fault_for_path(match, &in_scope)) {
    errno = EIO;
    return -1;
  }
  typedef int (*openat_fn)(int, const char *, int, ...);
  static openat_fn ropenat = real<openat_fn>("openat");
  int fd = ropenat(dirfd, path, flags, mode);
  if (fd >= 0 && in_scope) track_fd(fd, true);
  return fd;
}

int creat(const char *path, mode_t mode) {
  return open(path, O_CREAT | O_WRONLY | O_TRUNC, mode);
}

ssize_t read(int fd, void *buf, size_t count) {
  if (fault_for_fd(fd)) {
    errno = EIO;
    return -1;
  }
  static read_fn rread = real<read_fn>("read");
  return rread(fd, buf, count);
}

ssize_t write(int fd, const void *buf, size_t count) {
  if (fault_for_fd(fd)) {
    errno = EIO;
    return -1;
  }
  typedef ssize_t (*write_fn)(int, const void *, size_t);
  static write_fn rwrite = real<write_fn>("write");
  return rwrite(fd, buf, count);
}

ssize_t pread(int fd, void *buf, size_t count, off_t off) {
  if (fault_for_fd(fd)) {
    errno = EIO;
    return -1;
  }
  typedef ssize_t (*pread_fn)(int, void *, size_t, off_t);
  static pread_fn rpread = real<pread_fn>("pread");
  return rpread(fd, buf, count, off);
}

ssize_t pwrite(int fd, const void *buf, size_t count, off_t off) {
  if (fault_for_fd(fd)) {
    errno = EIO;
    return -1;
  }
  typedef ssize_t (*pwrite_fn)(int, const void *, size_t, off_t);
  static pwrite_fn rpwrite = real<pwrite_fn>("pwrite");
  return rpwrite(fd, buf, count, off);
}

ssize_t pread64(int fd, void *buf, size_t count, off_t off) {
  if (fault_for_fd(fd)) {
    errno = EIO;
    return -1;
  }
  typedef ssize_t (*pread_fn)(int, void *, size_t, off_t);
  static pread_fn rpread = real<pread_fn>("pread64");
  return rpread(fd, buf, count, off);
}

ssize_t pwrite64(int fd, const void *buf, size_t count, off_t off) {
  if (fault_for_fd(fd)) {
    errno = EIO;
    return -1;
  }
  typedef ssize_t (*pwrite_fn)(int, const void *, size_t, off_t);
  static pwrite_fn rpwrite = real<pwrite_fn>("pwrite64");
  return rpwrite(fd, buf, count, off);
}

int fsync(int fd) {
  if (fault_for_fd(fd)) {
    errno = EIO;
    return -1;
  }
  typedef int (*fsync_fn)(int);
  static fsync_fn rfsync = real<fsync_fn>("fsync");
  return rfsync(fd);
}

int fdatasync(int fd) {
  if (fault_for_fd(fd)) {
    errno = EIO;
    return -1;
  }
  typedef int (*fsync_fn)(int);
  static fsync_fn rfdatasync = real<fsync_fn>("fdatasync");
  return rfdatasync(fd);
}

int close(int fd) {
  track_fd(fd, false);
  static close_fn rclose = real<close_fn>("close");
  return rclose(fd);
}

}  // extern "C"
