// strobe-time-experiment: the ALIGNED strobe variant — instead of
// sleeping a fixed period between adjustments (strobe-time), each
// adjustment lands on the next exact multiple of <period> on the
// monotonic clock (tick = anchor + n*period), so clock jumps arrive on
// a precise grid however long settimeofday itself takes. C++ port of
// the reference's experimental tool
// (jepsen/resources/strobe-time-experiment.c:1-205 — unwired there,
// and not even compilable: its timespec_to_nanos declaration, `null`
// literal and inverted cmp loop are artifacts of abandonment; this
// port implements the evident intent with those bugs fixed), uploaded
// to nodes and compiled there by jepsen_tpu.nemesis.time.
//
// usage: strobe-time-experiment [--dry-run] <delta-ms> <period-ms>
//                               <duration-s>
//   Alternates the wall clock between its normal offset and
//   normal+delta at every period tick for duration seconds, restores
//   the normal offset, and prints the number of adjustments. With
//   --dry-run the full tick loop runs (including sleeps) but the wall
//   clock is never touched — for tests and rootless sanity checks.

#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <sys/time.h>
#include <thread>

namespace {

using Nanos = std::chrono::nanoseconds;
using Clock = std::chrono::steady_clock; // CLOCK_MONOTONIC

Nanos wall_now() {
  timeval tv{};
  struct timezone tz{};
  if (gettimeofday(&tv, &tz) != 0) {
    std::perror("gettimeofday");
    std::exit(1);
  }
  return Nanos{static_cast<int64_t>(tv.tv_sec) * 1000000000LL +
               static_cast<int64_t>(tv.tv_usec) * 1000LL};
}

struct timezone wall_tz() {
  timeval tv{};
  struct timezone tz{};
  if (gettimeofday(&tv, &tz) != 0) {
    std::perror("gettimeofday");
    std::exit(1);
  }
  return tz;
}

void set_wall_clock(Nanos t, struct timezone tz, bool dry_run) {
  if (dry_run)
    return;
  timeval tv{};
  tv.tv_sec = t.count() / 1000000000LL;
  tv.tv_usec = (t.count() % 1000000000LL) / 1000LL;
  if (settimeofday(&tv, &tz) != 0) {
    std::perror("settimeofday");
    std::exit(2);
  }
}

Nanos mono_now() {
  return std::chrono::duration_cast<Nanos>(
      Clock::now().time_since_epoch());
}

// The next grid point strictly after `now`:
// anchor + ceil((now - anchor) / period) * period
// (strobe-time-experiment.c:186-198's next_tick intent)
Nanos next_tick(Nanos period, Nanos anchor, Nanos now) {
  const int64_t elapsed = (now - anchor).count();
  const int64_t p = period.count();
  const int64_t n = elapsed / p + 1;
  return anchor + Nanos{n * p};
}

} // namespace

int main(int argc, char **argv) {
  bool dry_run = false;
  int arg0 = 1;
  if (argc > 1 && std::strcmp(argv[1], "--dry-run") == 0) {
    dry_run = true;
    arg0 = 2;
  }
  if (argc - arg0 != 3) {
    std::fprintf(stderr,
                 "usage: %s [--dry-run] <delta-ms> <period-ms> "
                 "<duration-s>\n"
                 "Alternates the wall clock between normal and "
                 "normal+delta at every exact multiple of period on "
                 "the monotonic clock, for duration seconds.\n",
                 argv[0]);
    return 1;
  }
  const Nanos delta{
      static_cast<int64_t>(std::atof(argv[arg0]) * 1000000.0)};
  const Nanos period{
      static_cast<int64_t>(std::atof(argv[arg0 + 1]) * 1000000.0)};
  const Nanos duration{
      static_cast<int64_t>(std::atof(argv[arg0 + 2]) * 1000000000.0)};
  if (period.count() <= 0) {
    std::fprintf(stderr, "period must be positive\n");
    return 1;
  }

  const Nanos normal_offset = wall_now() - mono_now();
  const Nanos weird_offset = normal_offset + delta;
  const struct timezone tz = wall_tz();

  const Nanos anchor = mono_now();
  const Nanos end = anchor + duration;
  bool weird = false;
  int64_t count = 0;

  // Bound on the TICK, not the current time: checking `mono_now() <
  // end` before sleeping would let the final adjustment land up to one
  // full period past the requested duration.
  for (;;) {
    const Nanos tick = next_tick(period, anchor, mono_now());
    if (tick >= end) break;
    std::this_thread::sleep_for(tick - mono_now());
    set_wall_clock(mono_now() + (weird ? normal_offset : weird_offset), tz,
                   dry_run);
    weird = !weird;
    count += 1;
  }

  set_wall_clock(mono_now() + normal_offset, tz, dry_run);
  std::printf("%lld\n", static_cast<long long>(count));
  return 0;
}
