// strobe-time: oscillate the wall clock by +/- <delta> ms every <period>
// ms for <duration> seconds, measured against CLOCK_MONOTONIC, then
// restore the normal offset. C++ port of the reference tool
// (jepsen/resources/strobe-time.c:1-171), uploaded to nodes and compiled
// there by jepsen_tpu.nemesis.time.
//
// usage: strobe-time [--dry-run] <delta-ms> <period-ms> <duration-s>
//   Prints the number of clock adjustments made. With --dry-run, runs
//   the full strobe loop (including the sleeps) but never touches the
//   wall clock — for tests and rootless sanity checks.

#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <sys/time.h>
#include <thread>

namespace {

using Nanos = std::chrono::nanoseconds;
using Clock = std::chrono::steady_clock; // CLOCK_MONOTONIC

// Wall clock now, as nanoseconds since the epoch (strobe-time.c:36-46)
Nanos wall_now() {
  timeval tv{};
  struct timezone tz{};
  if (gettimeofday(&tv, &tz) != 0) {
    std::perror("gettimeofday");
    std::exit(1);
  }
  return Nanos{static_cast<int64_t>(tv.tv_sec) * 1000000000LL +
               static_cast<int64_t>(tv.tv_usec) * 1000LL};
}

struct timezone wall_tz() {
  timeval tv{};
  struct timezone tz{};
  if (gettimeofday(&tv, &tz) != 0) {
    std::perror("gettimeofday");
    std::exit(1);
  }
  return tz;
}

// settimeofday from an epoch-nanos value (strobe-time.c:59-68)
void set_wall_clock(Nanos t, struct timezone tz, bool dry_run) {
  if (dry_run)
    return;
  timeval tv{};
  tv.tv_sec = static_cast<time_t>(t.count() / 1000000000LL);
  tv.tv_usec = static_cast<suseconds_t>((t.count() % 1000000000LL) / 1000LL);
  if (tv.tv_usec < 0) {
    tv.tv_sec -= 1;
    tv.tv_usec += 1000000;
  }
  if (settimeofday(&tv, &tz) != 0) {
    std::perror("settimeofday");
    std::exit(2);
  }
}

Nanos monotonic_now() {
  return std::chrono::duration_cast<Nanos>(Clock::now().time_since_epoch());
}

} // namespace

int main(int argc, char **argv) {
  bool dry_run = false;
  const char *pos[3] = {nullptr, nullptr, nullptr};
  int npos = 0;
  for (int i = 1; i < argc && npos <= 3; i++) {
    if (std::strcmp(argv[i], "--dry-run") == 0 ||
        std::strcmp(argv[i], "-n") == 0) {
      dry_run = true;
    } else if (npos < 3) {
      pos[npos++] = argv[i];
    }
  }
  if (npos < 3) {
    std::fprintf(stderr, "usage: %s [--dry-run] <delta> <period> <duration>\n",
                 argv[0]);
    std::fprintf(stderr,
                 "Delta and period are in ms, duration is in seconds. Every "
                 "period ms, adjusts the clock forward by delta ms, or, "
                 "alternatively, back by delta ms. Does this for duration "
                 "seconds, then exits. Useful for confusing the heck out of "
                 "systems that assume clocks are monotonic and linear.\n");
    return 1;
  }

  const Nanos delta{static_cast<int64_t>(std::atof(pos[0]) * 1e6)};
  const Nanos period{static_cast<int64_t>(std::atof(pos[1]) * 1e6)};
  const Nanos duration{static_cast<int64_t>(std::atof(pos[2]) * 1e9)};

  // How far ahead of the monotonic clock is wall time?
  // (strobe-time.c:133-135)
  const Nanos normal_offset = wall_now() - monotonic_now();
  const Nanos weird_offset = normal_offset + delta;
  const struct timezone tz = wall_tz();

  const Nanos end = monotonic_now() + duration;
  bool weird = false;
  int64_t count = 0;

  // Strobe until duration's up (strobe-time.c:152-165)
  while (monotonic_now() < end) {
    set_wall_clock(monotonic_now() + (weird ? normal_offset : weird_offset),
                   tz, dry_run);
    weird = !weird;
    count += 1;
    std::this_thread::sleep_for(period);
  }

  // Restore the normal wall/monotonic offset (strobe-time.c:167-169)
  set_wall_clock(monotonic_now() + normal_offset, tz, dry_run);
  std::printf("%lld\n", static_cast<long long>(count));
  return 0;
}
