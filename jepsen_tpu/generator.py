"""Op-scheduling DSL (reference: jepsen.generator, generator.clj).

A generator is asked for operations by worker threads: `op(test, process)`
returns an op dict (at minimum {"f": ..., "value": ...}) or None when
exhausted. Generators are shared, stateful, and thread-safe; blocking
inside op() is how time-based scheduling works (delays, staggering,
barriers) — exactly the reference's execution model (generator.clj:27-28).

Literal coercions (generator.clj:41-54): None is the void generator; a
dict emits itself forever; a callable is invoked as f(test, process) or
f(). Use once()/limit()/time_limit() to bound anything.

Thread routing: the dynamic *threads* binding (generator.clj:56-63) is a
per-worker-thread value set by the engine via with_threads(); on/reserve
and independent.concurrent_generator rebind it for sub-generators so
barriers synchronize over exactly the threads that can reach them.
"""

from __future__ import annotations

import inspect
import random
import threading
import time as _time
import weakref
from typing import Any, Callable, Iterable, Sequence

from .history import Op

NEMESIS = "nemesis"

_local = threading.local()


def current_threads():
    """The ordered collection of threads executing the current generator
    (generator.clj *threads*)."""
    return getattr(_local, "threads", None)


class _ThreadsBinding:
    def __init__(self, threads):
        self.threads = list(threads) if threads is not None else None

    def __enter__(self):
        self.prev = getattr(_local, "threads", None)
        _local.threads = self.threads
        return self

    def __exit__(self, *exc):
        _local.threads = self.prev


def with_threads(threads):
    """Context manager binding *threads* (generator.clj:66-72)."""
    return _ThreadsBinding(threads)


def process_to_thread(test, process):
    """process -> thread id: integers wrap mod concurrency; names (e.g.
    "nemesis") pass through (generator.clj:74-79)."""
    if isinstance(process, int):
        return process % test["concurrency"]
    return process


def process_to_node(test, process):
    """The node this process is likely talking to (generator.clj:81-88)."""
    thread = process_to_thread(test, process)
    if isinstance(thread, int):
        nodes = test["nodes"]
        return nodes[thread % len(nodes)]
    return None


class Generator:
    def op(self, test, process):
        raise NotImplementedError


class Void(Generator):
    def op(self, test, process):
        return None


void = Void()


class Repeat(Generator):
    """A literal op emitted forever (the reference's Object impl,
    generator.clj:45-46)."""

    def __init__(self, op_map: dict):
        self.op_map = dict(op_map)

    def op(self, test, process):
        return dict(self.op_map)


class FnGen(Generator):
    """Callables generate ops as f(test, process) or f() — arity decided
    by signature inspection at wrap time so a TypeError raised *inside*
    the function propagates instead of triggering a masking retry
    (generator.clj:48-54)."""

    def __init__(self, f: Callable):
        self.f = f
        try:
            sig = inspect.signature(f)
            self.two_arg = len(sig.parameters) >= 2 or any(
                p.kind == inspect.Parameter.VAR_POSITIONAL
                for p in sig.parameters.values()
            )
        except (TypeError, ValueError):
            self.two_arg = True

    def op(self, test, process):
        return self.f(test, process) if self.two_arg else self.f()


def to_gen(x) -> Generator:
    """Coerce literals to generators (generator.clj:41-54)."""
    if x is None:
        return void
    if isinstance(x, Generator):
        return x
    if isinstance(x, dict):
        return Repeat(x)
    if isinstance(x, Op):
        return Repeat(x.to_dict())
    if callable(x):
        return FnGen(x)
    raise TypeError(f"can't coerce {x!r} to a generator")


def op(gen, test, process):
    return to_gen(gen).op(test, process)


class InvalidOp(Exception):
    pass


def op_and_validate(gen, test, process):
    """op(), validating the result is None or a dict
    (generator.clj:30-39)."""
    o = op(gen, test, process)
    if o is not None and not isinstance(o, dict):
        raise InvalidOp(f"generator {gen!r} yielded invalid op {o!r}")
    return o


# ---------------------------------------------------------------------------
# Combinators

class FMap(Generator):
    """Replace op :f values via a mapping (generator.clj:142-155)."""

    def __init__(self, f_map, gen):
        self.f_map = f_map
        self.gen = to_gen(gen)

    def op(self, test, process):
        o = self.gen.op(test, process)
        if o is None:
            return None
        o = dict(o)
        f = o.get("f")
        o["f"] = self.f_map(f) if callable(self.f_map) else self.f_map.get(f, f)
        return o


def f_map(mapping, gen) -> FMap:
    return FMap(mapping, gen)


class DelayFn(Generator):
    """Each op takes f() extra seconds (generator.clj:176-190)."""

    def __init__(self, f, gen):
        self.f = f
        self.gen = to_gen(gen)

    def op(self, test, process):
        _time.sleep(self.f())
        return self.gen.op(test, process)


def delay_fn(f, gen) -> DelayFn:
    return DelayFn(f, gen)


def delay(dt, gen) -> DelayFn:
    assert dt > 0
    return DelayFn(lambda: dt, gen)


def sleep(dt) -> DelayFn:
    """Sleeps dt seconds, then yields None (generator.clj:197-200)."""
    return delay(dt, void)


def stagger(dt, gen) -> DelayFn:
    """Uniform random delay in [0, 2*dt) — mean dt — before each op
    (generator.clj:202-207)."""
    assert dt > 0
    return DelayFn(lambda: random.random() * 2 * dt, gen)


class DelayTil(Generator):
    """Emit ops as close as possible to multiples of dt seconds from an
    epoch — aligned invocations provoke races (generator.clj:209-234)."""

    def __init__(self, dt, gen, precache=True):
        self.dt = dt
        self.gen = to_gen(gen)
        self.precache = precache
        self.anchor = _time.monotonic()

    def _sleep_til_tick(self):
        now = _time.monotonic()
        tick = now + (self.dt - ((now - self.anchor) % self.dt))
        while True:
            remaining = tick - _time.monotonic()
            if remaining <= 1e-5:
                return
            _time.sleep(remaining)

    def op(self, test, process):
        if self.precache:
            o = self.gen.op(test, process)
            self._sleep_til_tick()
            return o
        self._sleep_til_tick()
        return self.gen.op(test, process)


def delay_til(dt, gen, precache=True) -> DelayTil:
    return DelayTil(dt, gen, precache)


class Once(Generator):
    """Invoke the underlying generator only once, globally
    (generator.clj:236-246)."""

    def __init__(self, gen):
        self.gen = to_gen(gen)
        self._emitted = False
        self._lock = threading.Lock()

    def op(self, test, process):
        with self._lock:
            if self._emitted:
                return None
            self._emitted = True
        return self.gen.op(test, process)


def once(gen) -> Once:
    return Once(gen)


class Derefer(Generator):
    """Deref a thunk to a generator on every op request — build the
    generator *later* (generator.clj:248-264)."""

    def __init__(self, thunk):
        self.thunk = thunk

    def op(self, test, process):
        return to_gen(self.thunk()).op(test, process)


def derefer(thunk) -> Derefer:
    return Derefer(thunk)


class LogGen(Generator):
    def __init__(self, msg):
        self.msg = msg

    def op(self, test, process):
        import logging

        logging.getLogger("jepsen_tpu").info(self.msg)
        return None


def log_star(msg) -> LogGen:
    return LogGen(msg)


def log(msg) -> Once:
    return once(LogGen(msg))


class Each(Generator):
    """A fresh copy of the underlying generator per process
    (generator.clj:283-306)."""

    def __init__(self, gen_fn):
        self.gen_fn = gen_fn
        self._gens: dict = {}
        self._lock = threading.Lock()

    def op(self, test, process):
        with self._lock:
            gen = self._gens.get(process)
            if gen is None:
                gen = to_gen(self.gen_fn())
                self._gens[process] = gen
        return gen.op(test, process)


def each(gen_fn) -> Each:
    return Each(gen_fn)


class SeqGen(Generator):
    """One op from each element in turn; a None op advances immediately;
    exhausted when the (possibly infinite) sequence ends
    (generator.clj:308-325)."""

    def __init__(self, coll: Iterable):
        self._it = iter(coll)
        self._lock = threading.Lock()
        self._done = False
        # draws so far — snapshot/restore replays this many next() calls
        # on a freshly-built identical iterator (drawing must therefore
        # be side-effect-free: elements act only when op() is called)
        self._n = 0

    def op(self, test, process):
        while True:
            with self._lock:
                if self._done:
                    return None
                try:
                    gen = next(self._it)
                    self._n += 1
                except StopIteration:
                    self._done = True
                    return None
            o = to_gen(gen).op(test, process)
            if o is not None:
                return o


def seq(coll) -> SeqGen:
    return SeqGen(coll)


def start_stop(t1, t2) -> SeqGen:
    """start after t1 seconds, stop after t2, forever
    (generator.clj:327-335)."""

    def cycle():
        while True:
            yield sleep(t1)
            yield {"type": "info", "f": "start"}
            yield sleep(t2)
            yield {"type": "info", "f": "stop"}

    return SeqGen(cycle())


class Mix(Generator):
    """Uniform random choice between generators (generator.clj:337-349).

    The draw happens inside op(), so a slow member (e.g. a delay/
    stagger wrapper that sleeps before yielding) blocks the calling
    worker and starves its siblings' share of a bounded time window.
    The reference has the same hazard — its mix also dispatches to the
    chosen generator synchronously — and we keep the semantics for
    parity; pace members with short intervals when mixing them under
    time_limit."""

    def __init__(self, gens: Sequence, rng: random.Random | None = None):
        self.gens = [to_gen(g) for g in gens]
        # seeded rng => reproducible interleaving (fault schedules)
        self.rng = rng or random

    def op(self, test, process):
        if not self.gens:
            return None
        return self.rng.choice(self.gens).op(test, process)


def mix(gens, rng: random.Random | None = None) -> Generator:
    return Mix(gens, rng=rng) if gens else void


class CasGen(Generator):
    """Random read/write/cas ops over a small integer field
    (generator.clj:352-365)."""

    def op(self, test, process):
        r = random.random()
        if r < 0.34:
            return {"type": "invoke", "f": "read", "value": None}
        if r < 0.67:
            return {"type": "invoke", "f": "write", "value": random.randrange(5)}
        return {
            "type": "invoke",
            "f": "cas",
            "value": (random.randrange(5), random.randrange(5)),
        }


cas = CasGen()


class QueueGen(Generator):
    """Random enqueue (consecutive ints) / dequeue mix
    (generator.clj:367-378)."""

    def __init__(self):
        self._i = -1
        self._lock = threading.Lock()

    def op(self, test, process):
        if random.random() < 0.5:
            with self._lock:
                self._i += 1
                v = self._i
            return {"type": "invoke", "f": "enqueue", "value": v}
        return {"type": "invoke", "f": "dequeue", "value": None}


def queue_gen() -> QueueGen:
    return QueueGen()


class DrainQueue(Generator):
    """After gen is exhausted, emit enough dequeues to match every
    attempted enqueue (generator.clj:380-396)."""

    def __init__(self, gen):
        self.gen = to_gen(gen)
        self._outstanding = 0
        self._lock = threading.Lock()

    def op(self, test, process):
        o = self.gen.op(test, process)
        if o is not None:
            if o.get("f") == "enqueue":
                with self._lock:
                    self._outstanding += 1
            return o
        with self._lock:
            self._outstanding -= 1
            remaining = self._outstanding
        if remaining >= 0:
            return {"type": "invoke", "f": "dequeue", "value": None}
        return None


def drain_queue(gen) -> DrainQueue:
    return DrainQueue(gen)


class Limit(Generator):
    """At most n ops, across all processes (generator.clj:398-407)."""

    def __init__(self, n, gen):
        self.gen = to_gen(gen)
        self._remaining = n
        self._lock = threading.Lock()

    def op(self, test, process):
        with self._lock:
            if self._remaining <= 0:
                return None
            self._remaining -= 1
        return self.gen.op(test, process)


def limit(n, gen) -> Limit:
    return Limit(n, gen)


DEADLINE_KEY = "_deadline"


class TimeLimit(Generator):
    """Ops until dt seconds after the first request. The reference bounds
    stuck *completions* too, by interrupting worker threads at the
    deadline (generator.clj:409-524); here every op emitted through the
    time limit carries the deadline (monotonic seconds) under
    DEADLINE_KEY, and the engine bounds that op's invoke wait by it
    (core.ClientWorker._invoke), abandoning the hung call and
    reincarnating the process on expiry. Attaching per-op keeps the bound
    scoped: ops drawn from sibling generators without a time limit are
    never capped by this one."""

    def __init__(self, dt, gen):
        self.dt = dt
        self.gen = to_gen(gen)
        self._deadline = None
        self._lock = threading.Lock()

    def op(self, test, process):
        with self._lock:
            if self._deadline is None:
                self._deadline = _time.monotonic() + self.dt
            deadline = self._deadline
        if _time.monotonic() >= deadline:
            return None
        r = self.gen.op(test, process)
        if r is None:
            return None
        r = dict(r)  # never mutate shared op literals
        prior = r.get(DEADLINE_KEY)
        r[DEADLINE_KEY] = deadline if prior is None else min(prior, deadline)
        return r


def time_limit(dt, gen) -> TimeLimit:
    return TimeLimit(dt, gen)


class Filter(Generator):
    """Only ops satisfying pred (generator.clj:526-540)."""

    def __init__(self, pred, gen):
        self.pred = pred
        self.gen = to_gen(gen)

    def op(self, test, process):
        while True:
            o = self.gen.op(test, process)
            if o is None:
                return None
            if self.pred(o):
                return o


def filter_gen(pred, gen) -> Filter:
    return Filter(pred, gen)


class On(Generator):
    """Forward to the source only for threads where pred(thread) is true;
    rebinds *threads* to the matching subset (generator.clj:542-552)."""

    def __init__(self, pred, gen):
        self.pred = pred
        self.gen = to_gen(gen)

    def op(self, test, process):
        if not self.pred(process_to_thread(test, process)):
            return None
        ts = current_threads()
        sub = [t for t in ts if self.pred(t)] if ts is not None else None
        with with_threads(sub):
            return self.gen.op(test, process)


def on(pred, gen) -> On:
    return On(pred, gen)


class Reserve(Generator):
    """reserve(n1, gen1, n2, gen2, ..., default): the first n1 threads of
    *threads* use gen1, the next n2 use gen2, ..., the rest use default.
    Rebinds *threads* per range (generator.clj:554-601)."""

    def __init__(self, *args):
        assert args, "reserve needs a default generator"
        *pairs, default = args
        assert len(pairs) % 2 == 0
        self.ranges = []
        lower = 0
        for i in range(0, len(pairs), 2):
            n, gen = pairs[i], pairs[i + 1]
            self.ranges.append((lower, lower + n, to_gen(gen)))
            lower += n
        self.default = to_gen(default)

    def op(self, test, process):
        threads = current_threads()
        if threads is None:
            threads = [NEMESIS] + list(range(test["concurrency"]))
        threads = list(threads)
        thread = process_to_thread(test, process)
        idx = threads.index(thread)
        for lower, upper, gen in self.ranges:
            if idx < upper:
                with with_threads(threads[lower:upper]):
                    return gen.op(test, process)
        lower = self.ranges[-1][1] if self.ranges else 0
        with with_threads(threads[lower:]):
            return self.default.op(test, process)


def reserve(*args) -> Reserve:
    return Reserve(*args)


class Concat(Generator):
    """First non-None op from each source in order, tracked per process
    (generator.clj:603-624)."""

    def __init__(self, *sources):
        self.sources = [to_gen(s) for s in sources]
        self._index: dict = {}
        self._lock = threading.Lock()

    def op(self, test, process):
        while True:
            with self._lock:
                i = self._index.get(process, 0)
            if i >= len(self.sources):
                return None
            o = self.sources[i].op(test, process)
            if o is not None:
                return o
            with self._lock:
                if self._index.get(process, 0) == i:
                    self._index[process] = i + 1


def concat(*sources) -> Concat:
    return Concat(*sources)


def nemesis(nemesis_gen, client_gen=None) -> Generator:
    """Route the nemesis to nemesis_gen; with client_gen, clients get that
    (generator.clj:626-635)."""
    if client_gen is None:
        return on(lambda t: t == NEMESIS, nemesis_gen)
    return concat(
        on(lambda t: t == NEMESIS, nemesis_gen),
        on(lambda t: t != NEMESIS, client_gen),
    )


def clients(client_gen) -> Generator:
    """Execute only on client threads (generator.clj:637-641)."""
    return on(lambda t: t != NEMESIS, client_gen)


class Await(Generator):
    """Block until fn completes (once, under a lock), then delegate
    (generator.clj:643-659)."""

    def __init__(self, f, gen=None):
        self.f = f
        self.gen = to_gen(gen)
        self._state = "waiting"
        self._lock = threading.Lock()

    def op(self, test, process):
        if self._state == "waiting":
            with self._lock:
                if self._state == "waiting":
                    self.f()
                    self._state = "ready"
        return self.gen.op(test, process)


def await_fn(f, gen=None) -> Await:
    return Await(f, gen)


_live_barriers = weakref.WeakSet()


def break_barriers() -> None:
    """Abort every live Synchronize barrier so workers blocked in a
    phases()/synchronize() wait wake up (with BrokenBarrierError) instead
    of deadlocking the run when another worker dies. Called from the
    engine's abort path (the reference interrupts the worker ThreadGroup
    instead, core.clj:232-237)."""
    for b in list(_live_barriers):
        try:
            b.abort()
        except Exception:  # noqa: BLE001
            pass


class Synchronize(Generator):
    """Block until every thread in *threads* is waiting on this generator,
    then proceed; synchronizes once (generator.clj:661-681)."""

    def __init__(self, gen):
        self.gen = to_gen(gen)
        self._barrier = None
        self._cleared = False
        self._lock = threading.Lock()

    def op(self, test, process):
        if not self._cleared:
            with self._lock:
                if self._barrier is None and not self._cleared:
                    threads = current_threads()
                    n = (
                        len(threads)
                        if threads is not None
                        else test["concurrency"] + 1
                    )
                    self._barrier = threading.Barrier(
                        n, action=self._clear
                    )
                    _live_barriers.add(self._barrier)
                barrier = self._barrier
            if barrier is not None and not self._cleared:
                barrier.wait()
        return self.gen.op(test, process)

    def _clear(self):
        self._cleared = True


def synchronize(gen) -> Synchronize:
    return Synchronize(gen)


def phases(*gens) -> Concat:
    """Like concat, but all threads must finish each phase before any
    moves on (generator.clj:683-687)."""
    return concat(*[synchronize(g) for g in gens])


def then(a, b):
    """b, synchronize, then a — backwards for pipeline composition
    (generator.clj:689-693)."""
    return concat(b, synchronize(a))


def barrier(gen):
    """When gen completes, synchronize, then None (generator.clj:700-703)."""
    return then(void, gen)


class SingleThreaded(Generator):
    """Exclusive lock around the underlying generator
    (generator.clj:695-698)."""

    def __init__(self, gen):
        self.gen = to_gen(gen)
        self._lock = threading.Lock()

    def op(self, test, process):
        with self._lock:
            return self.gen.op(test, process)


def singlethreaded(gen) -> SingleThreaded:
    return SingleThreaded(gen)


# ---------------------------------------------------------------------------
# Preemption: drain gate + checkpoint snapshot/restore

class Interruptible(Generator):
    """A drain gate: delegates until `event` is set, then yields None
    forever. core.prepare wraps the top-level generator in one so a
    SIGTERM (the TPU maintenance signal) can stop generation without
    touching workers — every thread sees exhaustion on its next draw
    and in-flight invokes drain through the normal timeout/:info path.
    Stateless, so it's a transparent node in checkpoint snapshots."""

    def __init__(self, gen, event: threading.Event):
        self.gen = to_gen(gen)
        self.event = event

    def op(self, test, process):
        if self.event.is_set():
            return None
        return self.gen.op(test, process)


def interruptible(gen, event: threading.Event) -> Interruptible:
    return Interruptible(gen, event)


def _children(g) -> list:
    """The sub-generators a combinator owns, in a fixed order (the
    snapshot/restore traversal spine)."""
    if isinstance(g, Mix):
        return list(g.gens)
    if isinstance(g, Concat):
        return list(g.sources)
    if isinstance(g, Reserve):
        return [gen for _, _, gen in g.ranges] + [g.default]
    sub = getattr(g, "gen", None)
    return [sub] if isinstance(sub, Generator) else []


def _state_of(g):
    """The JSON-serializable cursor state of one node, or None for
    stateless nodes (and unknown subclasses, which snapshot opaque)."""
    if isinstance(g, Once):
        return {"emitted": g._emitted}
    if isinstance(g, Limit):
        return {"remaining": g._remaining}
    if isinstance(g, TimeLimit):
        if g._deadline is None:
            return {"remaining": None}
        return {"remaining": max(0.0, g._deadline - _time.monotonic())}
    if isinstance(g, SeqGen):
        return {"n": g._n, "done": g._done}
    if isinstance(g, Concat):
        return {"index": [[p, i] for p, i in sorted(
            g._index.items(), key=lambda kv: str(kv[0]))]}
    if isinstance(g, Synchronize):
        return {"cleared": g._cleared}
    if isinstance(g, Mix):
        if isinstance(g.rng, random.Random):
            version, state, gauss = g.rng.getstate()
            return {"rng": [version, list(state), gauss]}
        return None
    if isinstance(g, QueueGen):
        return {"i": g._i}
    if isinstance(g, DrainQueue):
        return {"outstanding": g._outstanding}
    if isinstance(g, Await):
        return {"state": g._state}
    return None


def snapshot(gen) -> dict:
    """A JSON-serializable snapshot of a generator tree's cursors and
    rng states, for store.RunCheckpoint. Reads plain attributes under
    the GIL without taking generator locks, so it's safe from the
    checkpoint thread while workers run — a cursor may be at most one
    draw stale, and resume tolerates that: the WAL is the ground truth
    for which ops actually landed (at-least-once re-emission of the
    final in-flight draw is the documented contract).

    Unknown Generator subclasses become opaque leaves (type name only,
    no children): their state is not captured, and deterministic resume
    requires the schedule to be built from snapshot-aware combinators.
    Mix rng state is captured only for a private random.Random (the
    seeded-package case); the global `random` module is skipped."""
    g = to_gen(gen)
    node: dict = {"t": type(g).__name__}
    s = _state_of(g)
    if s is not None:
        node["s"] = s
    kids = _children(g)
    if kids:
        node["k"] = [snapshot(c) for c in kids]
    return node


def _restore_state(g, s) -> None:
    if s is None:
        return
    if isinstance(g, Once):
        g._emitted = bool(s["emitted"])
    elif isinstance(g, Limit):
        g._remaining = s["remaining"]
    elif isinstance(g, TimeLimit):
        rem = s.get("remaining")
        # remaining budget, not a fresh window: the run continues to
        # its ORIGINAL time limit
        g._deadline = None if rem is None else _time.monotonic() + rem
    elif isinstance(g, SeqGen):
        n = int(s.get("n", 0))
        for _ in range(n):
            try:
                next(g._it)
            except StopIteration:
                g._done = True
                break
        g._n = n
        g._done = g._done or bool(s.get("done"))
    elif isinstance(g, Concat):
        g._index = {p: i for p, i in s.get("index", [])}
    elif isinstance(g, Synchronize):
        g._cleared = bool(s["cleared"])
    elif isinstance(g, Mix):
        rng_s = s.get("rng")
        if rng_s is not None and isinstance(g.rng, random.Random):
            version, state, gauss = rng_s
            g.rng.setstate((version, tuple(state), gauss))
    elif isinstance(g, QueueGen):
        g._i = int(s["i"])
    elif isinstance(g, DrainQueue):
        g._outstanding = int(s["outstanding"])
    elif isinstance(g, Await):
        g._state = s["state"]


def restore(gen, node: dict) -> None:
    """Restore cursors saved by snapshot() into a structurally
    identical, freshly-rebuilt generator tree (same combinators in the
    same shape — i.e. reconstructed from the same seed/opts). SeqGen
    replays its draw count against the fresh iterator; TimeLimit gets
    its REMAINING budget, preserving the original deadline. Raises
    ValueError on any shape/type mismatch rather than silently
    resuming a different schedule."""
    g = to_gen(gen)
    if node.get("t") != type(g).__name__:
        raise ValueError(
            f"checkpoint shape mismatch: saved {node.get('t')!r}, "
            f"rebuilt {type(g).__name__!r}")
    _restore_state(g, node.get("s"))
    kids = _children(g)
    saved = node.get("k") or []
    if len(kids) != len(saved):
        raise ValueError(
            f"checkpoint shape mismatch under {node['t']}: "
            f"{len(saved)} saved children vs {len(kids)} rebuilt")
    for c, n in zip(kids, saved):
        restore(c, n)
