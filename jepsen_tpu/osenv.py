"""Operating-system provisioning protocol (reference: jepsen.os,
os.clj:4-14). Concrete distro implementations live in osdist.py."""

from __future__ import annotations


class OS:
    def setup(self, test, node) -> None:
        """Prepare the operating system on this node."""

    def teardown(self, test, node) -> None:
        """Clean up whatever setup did."""


class Noop(OS):
    pass


noop = Noop()
