"""Vectorized list-append cluster simulator.

One launch simulates a whole batch of independent clusters. Cluster
``i`` is fully determined by ``(wseeds[i], scheds[i])``: the workload
(coordinator choice, txn shapes, keys, read/append mix) is a pure
function of the workload seed via an FNV/murmur-style integer hash,
and the fault behavior is a pure function of the ``fuzz.schedule``
array. Everything is fixed-shape int32 tensor math — no data-dependent
shapes, no floats — so the SAME ``_sim_math`` body runs as jitted jax
on the device rung and as plain numpy on the host rung, bit-identically
(the host/device parity test pins this).

The model, in mop-time units (one txn slot = L mop-times):

* Txn slot ``s`` runs on coordinator ``coord[s]`` with up to ``L``
  micro-ops; mop ``(s, j)`` executes at effective time ``s*L + j``
  modified by faults. Appended values are globally unique
  (``vid = s*L + j + 1``).
* kill — a txn whose coordinator is inside a kill window FAILS (it is
  excluded from the trace); replication *to* a killed node is
  redelivered when the window ends.
* pause — a paused coordinator executes mops ``[0, p0)`` at slot time
  and defers mops ``[p0, L)`` to the window's end: one txn's effects
  interleave with seconds of other txns (the G0/G1c genesis).
* clock — a skewed coordinator's mops commit at ``t + p0 ± strobe``;
  skew reorders the serial append order across nodes.
* partition — replication crossing the cut is walled until the window
  ends; reads on the far side run stale (the G-single/G2 genesis).
* packet — seeded per-(mop, node) drops with delayed retransmission.
* corruption — masked replicas lose the recent tail of one key's log
  at ``t0`` and re-converge just after (bounded rollback).

The final append order per key ranks appends by ``(eff, mop-index)``;
a read at node ``n`` observes exactly the appends whose *delivery* to
``n`` precedes it — and its length is computed as the smallest
position not yet visible, so **every read is a prefix of the final
order**. Audit read txns run after every window/redelivery can land
and observe whole logs. Consequently decoded traces are always
inferable by checker/cycle/deps (no IllegalInference) and every
anomaly the checker reports is a real consequence of the schedule.

Engines ride a third supervisor singleton (``get_sim()``) with ladder
``sim_tpu -> sim_host``: a device failure mid-fuzz degrades the round
to host — with identical results — and never poisons the corpus.
"""

from __future__ import annotations

import functools
import os
import threading

import numpy as np

from ..checker import supervisor as sup_mod
from .schedule import (CLOCK, CORRUPT, DEFAULT_SPEC, KILL, PARTITION, PACKET,
                       PAUSE, SimSpec, canonicalize)

#: sentinel delivery/position for "never" — far beyond any real time
#: but safely inside int32 even after packet/retry arithmetic.
_BIG = np.int32(1 << 28)

#: pad / append / read codes in the ``kind`` output array.
KIND_APPEND = 0
KIND_READ = 1
KIND_PAD = 2

SIM_LADDER = ("sim_tpu", "sim_host")


def _make_hi(xp, np_mode: bool):
    """A 4-input integer hash -> uniform non-negative int32 arrays.

    murmur3-style finalizers over 32-bit lanes. The jax path uses
    native uint32 wraparound; the numpy path computes in uint64 and
    masks, which is bit-identical (products of 32-bit values never
    overflow 64 bits) without tripping numpy overflow warnings.
    """
    if np_mode:
        M = np.uint64(0xFFFFFFFF)

        def conv(x):
            return np.asarray(x).astype(np.uint64)

        def mul(a, c):
            return (a * np.uint64(c)) & M
    else:
        def conv(x):
            if isinstance(x, int):  # constants: dodge the int32 default
                return xp.uint32(x & 0xFFFFFFFF)
            return xp.asarray(x).astype(xp.uint32)

        def mul(a, c):
            return a * xp.uint32(c)

    def fmix(h):
        h = h ^ (h >> 16)
        h = mul(h, 0x85EBCA6B)
        h = h ^ (h >> 13)
        h = mul(h, 0xC2B2AE35)
        return h ^ (h >> 16)

    def hi(w, c, a, b):
        """hash(workload-seed, stream-constant, index-a, index-b) ->
        int32 in [0, 2^31); broadcasts like its array arguments."""
        h = fmix(conv(w) ^ conv(0x9E3779B9))
        h = fmix(h ^ mul(conv(a), 0x85EBCA6B))
        h = fmix(h ^ mul(conv(b), 0xC2B2AE35))
        h = fmix(h ^ mul(conv(c), 0x27D4EB2F))
        return (h & conv(0x7FFFFFFF)).astype(xp.int32)

    return hi


def _sim_math(xp, hi, scheds, wseeds, spec: SimSpec) -> dict:
    """The whole cluster batch, as one fixed-shape tensor program.

    scheds: [S, F, 6] int32, canonical. wseeds: [S] int (any width).
    Returns batch-first int32/bool arrays; see ``simulate_batch``.
    """
    S = scheds.shape[0]
    F, T, St, L = spec.faults, spec.txns, spec.slots, spec.mops
    N, K = spec.nodes, spec.keys
    i32 = xp.int32
    sarr = xp.arange(St, dtype=i32)                       # [St]
    jarr = xp.arange(L, dtype=i32)                        # [L]
    w2 = xp.asarray(wseeds).astype(i32)[:, None]          # [S,1]
    w3 = w2[:, :, None]                                   # [S,1,1]

    # -- workload: pure function of the workload seed ------------------
    is_audit = sarr >= T                                  # [St]
    coord = xp.where(is_audit, 0, hi(w2, 11, sarr, 0) % N)
    nmops = xp.where(is_audit, L, 1 + hi(w2, 12, sarr, 0) % L)
    rd = hi(w3, 13, sarr[None, :, None], jarr) % 2        # [S,St,L]
    key = hi(w3, 14, sarr[None, :, None], jarr) % K
    akey = (sarr[:, None] - T) * L + jarr[None, :]        # [St,L]
    active = xp.where(is_audit[:, None], akey < K,
                      jarr[None, :] < nmops[:, :, None])
    key = xp.where(is_audit[:, None], xp.clip(akey, 0, K - 1), key)
    kind = xp.where(~active, KIND_PAD,
                    xp.where(is_audit[:, None] | (rd == 1),
                             KIND_READ, KIND_APPEND))     # [S,St,L]

    # -- fault coverage at each txn's coordinator ----------------------
    fam, msk = scheds[:, :, 0], scheds[:, :, 1]           # [S,F]
    t0, t1 = scheds[:, :, 2], scheds[:, :, 3]
    p0, p1 = scheds[:, :, 4], scheds[:, :, 5]
    cbit = ((msk[:, :, None] >> coord[:, None, :]) & 1) == 1
    win = (t0[:, :, None] <= sarr) & (sarr < t1[:, :, None]) & ~is_audit
    cwin = cbit & win                                     # [S,F,St]
    failed = xp.any((fam[:, :, None] == KILL) & cwin, axis=1)
    pc = (fam[:, :, None] == PAUSE) & cwin
    pend = xp.max(xp.where(pc, t1[:, :, None], 0), axis=1)
    psplit = xp.max(xp.where(pc, p0[:, :, None], 0), axis=1)
    paused = xp.any(pc, axis=1)                           # [S,St]
    cc = (fam[:, :, None] == CLOCK) & cwin
    coff = xp.sum(xp.where(cc, p0[:, :, None], 0), axis=1)
    camp = xp.max(xp.where(cc, p1[:, :, None], 0), axis=1)

    # -- effective (commit-order) time of every mop --------------------
    base = sarr[None, :, None] * L + jarr                 # [1,St,L]
    defer = paused[:, :, None] & (jarr[None, None, :] >= psplit[:, :, None])
    basew = xp.where(defer, pend[:, :, None] * L + jarr, base)
    denom = 2 * camp[:, :, None] + 1
    jit_ = hi(w3, 16, sarr[None, :, None], jarr) % denom - camp[:, :, None]
    effw = xp.maximum(basew + coff[:, :, None] + jit_, 0)
    abase = (spec.audit_t0 + sarr[None, :, None] - T) * L + jarr
    eff = xp.where(is_audit[None, :, None], abase, effw)  # [S,St,L]

    # -- flatten to mop index m = s*L + j ------------------------------
    Mtot = St * L
    marr = xp.arange(Mtot, dtype=i32)
    effm = eff.reshape(S, Mtot)
    keym = key.reshape(S, Mtot)
    kindm = kind.reshape(S, Mtot)
    sendm = xp.broadcast_to(coord[:, :, None], (S, St, L)).reshape(S, Mtot)
    failm = xp.broadcast_to(failed[:, :, None], (S, St, L)).reshape(S, Mtot)
    vapp = (kindm == KIND_APPEND) & ~failm
    vread = (kindm == KIND_READ) & ~failm

    # -- final per-key append order: rank by (eff, mop index) ----------
    keyeq = keym[:, :, None] == keym[:, None, :]          # [S,M,M']
    earlier = (effm[:, None, :] < effm[:, :, None]) \
        | ((effm[:, None, :] == effm[:, :, None])
           & (marr[None, :] < marr[:, None]))
    pos = xp.sum(vapp[:, None, :] & keyeq & earlier, axis=2).astype(i32)

    # -- delivery time of each append at each node ---------------------
    narr = xp.arange(N, dtype=i32)
    deliv = effm[:, :, None] + xp.zeros((1, 1, N), dtype=i32)
    for f in range(F):  # static unroll; one family per slot
        fa = fam[:, f][:, None, None]
        mk = msk[:, f][:, None, None]
        a0 = t0[:, f][:, None, None] * L
        a1 = t1[:, f][:, None, None] * L
        q0 = p0[:, f][:, None, None]
        q1 = p1[:, f][:, None, None]
        sb = ((mk >> sendm[:, :, None]) & 1) == 1         # [S,M,1]
        rb = ((mk >> narr[None, None, :]) & 1) == 1       # [S,1,N]
        nonlocal_ = sendm[:, :, None] != narr[None, None, :]
        # windows test the CURRENT delivery time, so faults cascade
        # (a partition can push a delivery into a kill window) in a
        # fixed slot order — deterministic on both engines.
        inw = (a0 <= deliv) & (deliv < a1)
        deliv = xp.where((fa == PARTITION) & (sb ^ rb) & inw, a1, deliv)
        hd = hi(w3, 170 + f, marr[None, :, None], narr[None, None, :])
        inw = (a0 <= deliv) & (deliv < a1)
        drop = (fa == PACKET) & (sb | rb) & nonlocal_ & inw \
            & (hd % 16 < q0)
        extra = 1 + (hd >> 4) % xp.maximum(q1 * L, 1)
        deliv = xp.where(drop, deliv + extra, deliv)
        inw = (a0 <= deliv) & (deliv < a1)
        deliv = xp.where((fa == KILL) & rb & inw, a1, deliv)
        inw = (a0 <= deliv) & (deliv < a1)
        deliv = xp.where((fa == PAUSE) & rb & inw, a1, deliv)
        roll = (fa == CORRUPT) & rb & (keym[:, :, None] == q0) \
            & (a0 - q1 * L <= deliv) & (deliv < a0)
        deliv = xp.where(roll, a0 + 1, deliv)
    local = narr[None, None, :] == sendm[:, :, None]
    deliv = xp.where(local, effm[:, :, None], deliv)      # own node: instant
    deliv = xp.where(vapp[:, :, None], deliv, _BIG)

    # -- reads: longest not-yet-visible position bounds the prefix -----
    deliv_t = xp.transpose(deliv, (0, 2, 1))              # [S,N,M']
    dsel = xp.take_along_axis(deliv_t, sendm[:, :, None], axis=1)
    e_r = effm[:, :, None]
    vis = (dsel < e_r) | ((dsel == e_r) & (marr[None, :] < marr[:, None]))
    inv = vapp[:, None, :] & keyeq & ~vis
    minpos = xp.min(xp.where(inv, pos[:, None, :], _BIG), axis=2)
    total = xp.sum(vapp[:, None, :] & keyeq, axis=2).astype(i32)
    rlen = xp.minimum(minpos, total)

    return {
        "coord": coord.astype(i32),
        "failed": failm.reshape(S, St, L)[:, :, 0],
        "kind": kindm.reshape(S, St, L),
        "key": keym.reshape(S, St, L),
        "eff": effm.reshape(S, St, L),
        "pos": xp.where(vapp, pos, -1).reshape(S, St, L),
        "rlen": xp.where(vread, rlen, -1).reshape(S, St, L),
    }


def _as_batch(scheds, wseeds, spec: SimSpec):
    scheds = np.asarray(scheds, dtype=np.int32)
    if scheds.ndim == 2:
        scheds = scheds[None]
    if scheds.shape[1:] != (spec.faults, 6):
        raise ValueError(f"schedule batch shape {scheds.shape}")
    wseeds = np.atleast_1d(np.asarray(wseeds, dtype=np.int64))
    if wseeds.shape[0] != scheds.shape[0]:
        raise ValueError("wseeds/scheds batch mismatch")
    # fold to non-negative 31-bit — the hash's seed lane width
    wseeds = (wseeds & 0x7FFFFFFF).astype(np.int32)
    return scheds, wseeds


def sim_host(scheds, wseeds, spec: SimSpec = DEFAULT_SPEC) -> dict:
    """Numpy floor engine: one call, whole batch, no dependencies."""
    scheds, wseeds = _as_batch(scheds, wseeds, spec)
    hi = _make_hi(np, np_mode=True)
    out = _sim_math(np, hi, scheds, wseeds, spec)
    return {k: np.asarray(v) for k, v in out.items()}


@functools.lru_cache(maxsize=8)
def _jitted(spec: SimSpec):
    import jax
    import jax.numpy as jnp

    hi = _make_hi(jnp, np_mode=False)

    def f(scheds, wseeds):
        return _sim_math(jnp, hi, scheds, wseeds, spec)

    return jax.jit(f)


def sim_device(scheds, wseeds, spec: SimSpec = DEFAULT_SPEC) -> dict:
    """Jitted jax engine: ONE device launch executes the whole batch
    of seeded clusters end-to-end."""
    import jax

    scheds, wseeds = _as_batch(scheds, wseeds, spec)
    out = _jitted(spec)(scheds, wseeds)
    out = jax.device_get(out)
    return {k: np.asarray(v) for k, v in out.items()}


def probe(spec: SimSpec = DEFAULT_SPEC) -> bool:
    """Can the device engine compile at all? (supervisor probe hook)"""
    try:
        sim_device(np.zeros((1, spec.faults, 6), np.int32), [0], spec)
        return True
    except Exception:  # noqa: BLE001 — any failure means "no"
        return False


# -- supervision --------------------------------------------------------
#
# Third supervisor singleton (after the search-engine and closure
# ones): the work unit is a list of (wseed, schedule) cluster configs
# and `model` carries the SimSpec. Rung names are distinct so breaker
# state and telemetry never collide with the other ladders.

def _split(out: dict, n: int) -> list:
    return [{k: np.asarray(v[i]) for k, v in out.items()} for i in range(n)]


def _stack(model, ess):
    spec = model or DEFAULT_SPEC
    scheds = np.stack([np.asarray(e[1], dtype=np.int32) for e in ess])
    wseeds = np.array([int(e[0]) for e in ess], dtype=np.int64)
    return spec, scheds, wseeds


def _run_sim_tpu(model, ess, max_steps=None, time_limit=None):
    spec, scheds, wseeds = _stack(model, ess)
    return _split(sim_device(scheds, wseeds, spec), len(ess))


def _run_sim_host(model, ess, max_steps=None, time_limit=None):
    spec, scheds, wseeds = _stack(model, ess)
    return _split(sim_host(scheds, wseeds, spec), len(ess))


def _elig_sim_tpu(model, ess) -> bool:
    try:
        import jax  # noqa: F401
        return True
    except ImportError:
        return False


def sim_registry() -> dict:
    return {"sim_tpu": _run_sim_tpu, "sim_host": _run_sim_host}


def sim_eligibility() -> dict:
    return {"sim_tpu": _elig_sim_tpu,
            "sim_host": lambda model, ess: True}


_sim_sup: sup_mod.Supervisor | None = None
_sim_lock = threading.Lock()


def get_sim() -> sup_mod.Supervisor:
    """The per-process sim supervisor (same config env knobs as the
    checker's, its own registry/breaker/telemetry)."""
    global _sim_sup
    with _sim_lock:
        if _sim_sup is None:
            _sim_sup = sup_mod.Supervisor(
                sup_mod._env_config(), registry=sim_registry(),
                eligibility=sim_eligibility())
        return _sim_sup


def _reset_sim_for_tests(sup: sup_mod.Supervisor | None = None) -> None:
    global _sim_sup
    with _sim_lock:
        _sim_sup = sup


def simulate_batch(scheds, wseeds, spec: SimSpec = DEFAULT_SPEC,
                   engine: str | None = None,
                   deadline: float | None = None) -> list:
    """Simulate a batch of clusters; returns one result dict per
    cluster (int32/bool numpy arrays):

    coord [slots], failed [slots], kind/key/eff/pos/rlen [slots, mops].

    engine=None rides the supervised SIM_LADDER (device, host floor —
    a device failure degrades the batch, never aborts it); "host" /
    "tpu" pin a rung, bypassing supervision (tests, parity runs).
    """
    scheds = np.asarray(scheds, dtype=np.int32)
    if scheds.ndim == 2:
        scheds = scheds[None]
    scheds = np.stack([canonicalize(s, spec) for s in scheds])
    wseeds = np.atleast_1d(np.asarray(wseeds, dtype=np.int64))
    if engine == "host":
        return _split(sim_host(scheds, wseeds, spec), scheds.shape[0])
    if engine in ("tpu", "device", "sim_tpu"):
        return _split(sim_device(scheds, wseeds, spec), scheds.shape[0])
    if engine is not None:
        raise ValueError(f"unknown sim engine: {engine}")
    ess = list(zip(wseeds.tolist(), list(scheds)))
    return get_sim().run(spec, ess, ladder=SIM_LADDER, deadline=deadline,
                         on_exhausted="raise")


#: JEPSEN_TPU_SIM_ENGINE pins the fuzz loop's sim rung (mirrors the
#: checker's engine pinning envs; used by the chaos driver to keep
#: SIGKILL-resume rounds byte-reproducible without jax warmup cost).
def env_engine() -> str | None:
    return os.environ.get("JEPSEN_TPU_SIM_ENGINE") or None
