"""The coverage-guided fuzz loop and its crash-consistent corpus.

Each round builds a population of cluster configs — half fresh seeded
schedules, half mutants of corpus schedules (fuzz.schedule.mutate,
with donor splicing) — simulates them in one supervised batch
(fuzz.sim), scores them in one supervised closure batch (fuzz.score),
and retains every config whose coverage key (fuzz.score.coverage_key)
is new. Discovered-anomaly entries are additionally rendered to the
replay-parity corpus (an anomalies.jsonl the ``fuzz`` block of
tools/replay_parity.py re-checks on every engine).

Crash consistency rides the PR 5 discipline: corpus state is ONE json
document committed per round via store.atomic_write_json (write-temp
-> fsync -> rename, ``.prev`` rotation), and anomalies.jsonl is
re-derived from that state on the same commit. A round is a pure
function of (fuzz seed, round number, corpus state at round start) —
NO wall clock or unseeded randomness — so a SIGKILL anywhere simply
replays the interrupted round byte-identically on restart: entry ids
are content fingerprints, coverage keys collide exactly, and the
corpus converges to the same state as an uninterrupted run
(exactly-once semantics by idempotent replay; tests/test_fuzz_chaos.py
pins this with a real mid-round SIGKILL).
"""

from __future__ import annotations

import dataclasses
import json
import os
import random

import numpy as np

from .. import store
from .schedule import (DEFAULT_SPEC, FAMILIES, SimSpec, derive_seed,
                       fingerprint, mutate, random_schedule,
                       schedule_from_lists, schedule_to_lists)
from .score import score_batch
from .sim import env_engine, simulate_batch

STATE_FILE = "corpus.json"
ANOMALIES_FILE = "anomalies.jsonl"


def _spec_doc(spec: SimSpec) -> dict:
    return dataclasses.asdict(spec)


def spec_from_doc(doc: dict) -> SimSpec:
    return SimSpec(**{k: int(v) for k, v in doc.items()}).validate()


class Corpus:
    """The on-disk fuzz corpus: one state document, committed
    atomically once per round, plus the derived anomalies.jsonl."""

    def __init__(self, dir_path: str, spec: SimSpec = DEFAULT_SPEC,
                 seed: int = 0):
        self.dir = dir_path
        self.path = os.path.join(dir_path, STATE_FILE)
        self.anomalies_path = os.path.join(dir_path, ANOMALIES_FILE)
        self.state = self._load() or {
            "version": 1,
            "seed": int(seed),
            "spec": _spec_doc(spec),
            "round": 0,
            "clusters-run": 0,
            "coverage": {},      # coverage key -> entry id
            "entries": {},       # entry id -> entry (insertion order!)
            "anomalies": [],     # entry ids, discovery order
            "first-anomaly": None,
        }
        self.spec = spec_from_doc(self.state["spec"])

    def _load(self):
        """corpus.json, falling back to the rotated .prev — the same
        torn-tail tolerance RunCheckpoint has."""
        for p in (self.path, self.path + ".prev"):
            try:
                with open(p) as fh:
                    doc = json.load(fh)
                if doc.get("version") == 1:
                    return doc
            except (OSError, ValueError):
                continue
        return None

    def commit(self) -> None:
        """One atomic commit: derived anomalies.jsonl first, then the
        authoritative state document. A SIGKILL between the two leaves
        a jsonl from the NEW state with the OLD corpus.json — the next
        commit rewrites the jsonl from authoritative state, so it can
        never diverge for longer than the interrupted round's replay."""
        self._write_anomalies()
        store.atomic_write_json(self.path, self.state, rotate_prev=True)

    def _write_anomalies(self) -> None:
        os.makedirs(self.dir, exist_ok=True)
        tmp = self.anomalies_path + ".tmp"
        with open(tmp, "w") as fh:
            for eid in self.state["anomalies"]:
                e = self.state["entries"][eid]
                fh.write(json.dumps(
                    {"id": eid, "wseed": e["wseed"],
                     "schedule": e["schedule"],
                     "spec": self.state["spec"],
                     "types": e["types"],
                     "cycle-count": e["cycle-count"],
                     "coverage": e["coverage"],
                     "round": e["round"]},
                    sort_keys=True) + "\n")
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self.anomalies_path)

    # -- views ------------------------------------------------------------

    def entries(self) -> list:
        return list(self.state["entries"].values())

    def anomaly_entries(self) -> list:
        return [self.state["entries"][i] for i in self.state["anomalies"]]

    def anomaly_types(self) -> list:
        ts = {t for e in self.anomaly_entries() for t in e["types"]}
        return sorted(ts)

    def summary(self) -> dict:
        return {
            "seed": self.state["seed"],
            "round": self.state["round"],
            "clusters-run": self.state["clusters-run"],
            "coverage-buckets": len(self.state["coverage"]),
            "entries": len(self.state["entries"]),
            "anomalies": len(self.state["anomalies"]),
            "anomaly-types": self.anomaly_types(),
            "first-anomaly": self.state["first-anomaly"],
        }


class FuzzLoop:
    """Deterministic coverage-guided fuzzing over cluster schedules.

    ``round_hook(round_no)`` is a test seam invoked after a round's
    results are folded into in-memory state but BEFORE the commit —
    exactly where a crash is most interesting (the chaos driver
    SIGKILLs there)."""

    def __init__(self, corpus_dir: str, spec: SimSpec = DEFAULT_SPEC,
                 seed: int = 0, clusters: int = 256, families=None,
                 engine: str | None = None, score_engine: str | None = None,
                 round_hook=None, score_budget_s: float | None = None):
        if clusters < 2:
            raise ValueError("clusters must be >= 2")
        self.corpus = Corpus(corpus_dir, spec, seed)
        self.spec = self.corpus.spec
        self.seed = int(self.corpus.state["seed"])
        self.clusters = int(clusters)
        self.families = tuple(families) if families else FAMILIES
        self.engine = engine if engine is not None else env_engine()
        self.score_engine = score_engine
        self.round_hook = round_hook
        # wall-clock bound per round's scoring launch: traces whose
        # closures don't fit score unknown (never kept in the corpus)
        # instead of wedging the whole campaign
        self.score_budget_s = score_budget_s

    # -- population -------------------------------------------------------

    def _population(self, rnd: int) -> list:
        """The round's cluster configs: (wseed, schedule, parent-id,
        op). Pure function of (seed, round, corpus state) — determinism
        is what makes crash replay exactly-once."""
        entries = self.corpus.entries()
        pop = []
        for i in range(self.clusters):
            sd = derive_seed(self.seed, rnd, i)
            wseed = derive_seed(self.seed, rnd, i, 0xA) & 0x7FFFFFFF
            rng = random.Random(sd)
            if entries and i % 2 == 1:
                parent = rng.choice(entries)
                donor = rng.choice(entries)
                sched = mutate(schedule_from_lists(parent["schedule"],
                                                   self.spec),
                               sd, self.spec,
                               donor=schedule_from_lists(donor["schedule"],
                                                         self.spec),
                               families=self.families)
                if rng.random() < 0.5:
                    # keep the parent's workload: mutate ONLY the
                    # schedule, so coverage gains are attributable
                    wseed = int(parent["wseed"])
                pop.append((wseed, sched, parent["id"], "mutate"))
            else:
                sched = random_schedule(sd, self.spec,
                                        families=self.families)
                pop.append((wseed, sched, None, "seed"))
        return pop

    # -- rounds -----------------------------------------------------------

    def _fold(self, rnd: int, pop: list, scores: list) -> dict:
        st = self.corpus.state
        kept = new_anoms = 0
        for (wseed, sched, parent, op), score in zip(pop, scores):
            cov = score["coverage"]
            if cov == "unknown" or cov in st["coverage"]:
                continue
            eid = fingerprint(sched, wseed)
            if eid in st["entries"]:
                continue
            st["entries"][eid] = {
                "id": eid, "wseed": int(wseed),
                "schedule": schedule_to_lists(sched),
                "coverage": cov, "types": score["anomaly-types"],
                "cycle-count": score["cycle-count"],
                "round": rnd, "parent": parent, "op": op,
            }
            st["coverage"][cov] = eid
            kept += 1
            if score["anomaly-types"]:
                st["anomalies"].append(eid)
                new_anoms += 1
                if st["first-anomaly"] is None:
                    st["first-anomaly"] = {
                        "round": rnd,
                        "clusters": st["clusters-run"] + len(pop),
                        "types": score["anomaly-types"],
                    }
        st["clusters-run"] += len(pop)
        return {"round": rnd, "clusters": len(pop), "kept": kept,
                "new-anomalies": new_anoms}

    def run_round(self) -> dict:
        rnd = int(self.corpus.state["round"])
        pop = self._population(rnd)
        scheds = np.stack([p[1] for p in pop])
        wseeds = np.array([p[0] for p in pop], dtype=np.int64)
        results = simulate_batch(scheds, wseeds, self.spec,
                                 engine=self.engine)
        budget = None
        if self.score_budget_s is not None:
            import time

            budget = time.monotonic() + self.score_budget_s
        scores = score_batch(results, self.spec, scheds=scheds,
                             engine=self.score_engine, budget=budget)
        stats = self._fold(rnd, pop, scores)
        if self.round_hook is not None:
            self.round_hook(rnd)
        self.corpus.state["round"] = rnd + 1
        self.corpus.commit()
        return stats

    def run(self, rounds: int) -> dict:
        """Run until the corpus has seen ``rounds`` rounds total (a
        resumed loop only runs the remainder)."""
        per_round = []
        while int(self.corpus.state["round"]) < rounds:
            per_round.append(self.run_round())
        return {**self.corpus.summary(), "per-round": per_round}


def run_fuzz(opts: dict) -> dict:
    """CLI body for ``jepsen-tpu fuzz`` (kept importable for tests and
    the bench lane)."""
    spec = SimSpec(
        nodes=int(opts.get("nodes_n") or DEFAULT_SPEC.nodes),
        keys=int(opts.get("keys") or DEFAULT_SPEC.keys),
        txns=int(opts.get("txns") or DEFAULT_SPEC.txns),
        mops=int(opts.get("mops") or DEFAULT_SPEC.mops),
        faults=int(opts.get("fault_slots") or DEFAULT_SPEC.faults),
    ).validate()
    families = None
    if opts.get("families"):
        families = [f.strip() for f in str(opts["families"]).split(",")
                    if f.strip()]
        bad = [f for f in families if f not in FAMILIES]
        if bad:
            raise ValueError(f"unknown fault families: {bad} "
                             f"(known: {list(FAMILIES)})")
    deadline_ms = opts.get("deadline_ms")
    loop = FuzzLoop(
        opts["corpus_dir"], spec=spec,
        seed=int(opts.get("seed") or 0),
        clusters=int(opts.get("clusters") or 256),
        families=families,
        engine=opts.get("engine"),
        score_budget_s=(max(1, int(deadline_ms)) / 1000.0
                        if deadline_ms is not None else None),
    )
    return loop.run(int(opts.get("rounds") or 4))
