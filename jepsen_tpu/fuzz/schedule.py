"""Fixed-shape array encoding of nemesis fault schedules.

A schedule is an int32 array of shape ``[F, 6]`` — ``F`` fault slots,
each ``(family, mask, t0, t1, p0, p1)``:

========  =====================================================
field     meaning
========  =====================================================
family    0 none | 1 partition | 2 clock | 3 kill | 4 pause |
          5 corruption | 6 packet
mask      node bitmask (bit ``n`` = node ``n`` affected)
t0, t1    fault window in txn-slot units, ``0 <= t0 < t1 <= T``
p0, p1    family parameters (see ``canonicalize``)
========  =====================================================

Family parameters:

* partition — unused; the mask IS the grudge (masked nodes are cut
  from unmasked nodes, both directions).
* clock — ``p0``: skew offset in mop-time units, ``[-2L, 2L]``;
  ``p1``: strobe amplitude in mop-time units, ``[0, L]``.
* kill — unused; masked nodes are down for the window (their
  coordinated txns fail; replication to them is redelivered at
  ``t1``).
* pause — ``p0``: split point ``[1, L-1]``; a paused coordinator
  executes mops ``[0, p0)`` at the txn's slot time and defers mops
  ``[p0, L)`` to the window's end.
* corruption — ``p0``: key index; ``p1``: rollback depth window in
  txn-slots ``[1, 8]``. At ``t0`` the masked replicas lose their
  tail of key ``p0``'s log received in the last ``p1`` slots and
  re-converge just after ``t0``.
* packet — ``p0``: drop rate in sixteenths ``[1, 16]``; ``p1``: max
  redelivery delay in txn-slots ``[1, 8]``. Dropped sends to/from
  masked nodes are retransmitted with a seeded delay.

Everything here is host-side numpy + ``random.Random`` (both
platform-stable); the arrays feed ``fuzz.sim`` verbatim. The
``to_nemesis_doc`` bridge renders an array schedule as a
``nemesis/combined.py`` schedule document so fuzz-discovered
schedules replay through the real nemesis path via
``jepsen-tpu test --nemesis-schedule <file>``.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import random

import numpy as np

NONE = 0
PARTITION = 1
CLOCK = 2
KILL = 3
PAUSE = 4
CORRUPT = 5
PACKET = 6

FAMILIES = ("partition", "clock", "kill", "pause", "corruption", "packet")
FAMILY_CODE = {name: i + 1 for i, name in enumerate(FAMILIES)}
CODE_FAMILY = {i + 1: name for i, name in enumerate(FAMILIES)}

FIELDS = ("family", "mask", "t0", "t1", "p0", "p1")

# Bounds shared with fuzz.sim: redelivery / rollback windows never
# exceed MAX_SPAN txn-slots, so audit reads placed after
# 2*T + 2*MAX_SPAN slots observe every delivery.
MAX_SPAN = 8
MAX_SKEW_MOPS = 2  # clock skew bound, in units of L mop-times


@dataclasses.dataclass(frozen=True)
class SimSpec:
    """Static shape of one simulated cluster (compile-time constants)."""

    nodes: int = 5
    keys: int = 8
    txns: int = 24
    mops: int = 4
    faults: int = 8

    @property
    def audits(self) -> int:
        """Final audit read txns: enough read mops to cover every key."""
        return -(-self.keys // self.mops)

    @property
    def slots(self) -> int:
        """Total txn slots simulated: work txns + audit txns."""
        return self.txns + self.audits

    @property
    def audit_t0(self) -> int:
        """Slot time of the first audit txn — after every fault window,
        redelivery, and clock excursion can land."""
        return 2 * self.txns + 2 * MAX_SPAN

    def validate(self):
        if not (1 <= self.nodes <= 16):
            raise ValueError(f"nodes out of range: {self.nodes}")
        if self.mops < 2:
            raise ValueError("need >= 2 mops per txn")
        if self.txns < 2:
            raise ValueError("need >= 2 txn slots")
        if self.keys < 1 or self.faults < 1:
            raise ValueError("keys and faults must be positive")
        return self


DEFAULT_SPEC = SimSpec()


def _mix64(x: int) -> int:
    """splitmix64 finalizer — derive independent integer seeds without
    relying on hash() (PYTHONHASHSEED) or platform word size."""
    x &= 0xFFFFFFFFFFFFFFFF
    x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9 & 0xFFFFFFFFFFFFFFFF
    x = (x ^ (x >> 27)) * 0x94D049BB133111EB & 0xFFFFFFFFFFFFFFFF
    return x ^ (x >> 31)


def derive_seed(seed: int, *salts: int) -> int:
    """Stable sub-seed derivation: pure function of (seed, salts)."""
    x = _mix64(seed ^ 0x6A09E667F3BCC908)
    for s in salts:
        x = _mix64(x ^ _mix64(s ^ 0xBB67AE8584CAA73B))
    return x


def empty_schedule(spec: SimSpec = DEFAULT_SPEC) -> np.ndarray:
    return np.zeros((spec.faults, 6), dtype=np.int32)


def canonicalize(sched: np.ndarray, spec: SimSpec = DEFAULT_SPEC) -> np.ndarray:
    """Clamp a schedule into the legal envelope (idempotent).

    Mutations may push fields out of range; the simulator only accepts
    canonical schedules, so every generator/mutator funnels through
    here. Slots with family NONE or an empty mask are zeroed whole so
    byte-comparison of canonical schedules is meaningful.
    """
    s = np.array(sched, dtype=np.int32, copy=True)
    if s.shape != (spec.faults, 6):
        raise ValueError(f"schedule shape {s.shape} != {(spec.faults, 6)}")
    T, L = spec.txns, spec.mops
    full_mask = (1 << spec.nodes) - 1
    for i in range(spec.faults):
        fam, mask, t0, t1, p0, p1 = (int(v) for v in s[i])
        if fam < NONE or fam > PACKET:
            fam = NONE
        mask &= full_mask
        if fam == NONE or mask == 0:
            s[i] = 0
            continue
        t0 = max(0, min(int(t0), T - 1))
        t1 = max(t0 + 1, min(int(t1), T))
        if fam == PARTITION or fam == KILL:
            p0 = p1 = 0
        elif fam == CLOCK:
            p0 = max(-MAX_SKEW_MOPS * L, min(int(p0), MAX_SKEW_MOPS * L))
            p1 = max(0, min(int(p1), L))
        elif fam == PAUSE:
            p0 = max(1, min(int(p0), L - 1))
            p1 = 0
        elif fam == CORRUPT:
            p0 = int(p0) % spec.keys
            p1 = max(1, min(int(p1), MAX_SPAN))
        elif fam == PACKET:
            p0 = max(1, min(int(p0), 16))
            p1 = max(1, min(int(p1), MAX_SPAN))
        s[i] = (fam, mask, t0, t1, p0, p1)
    return s


def _random_slot(rng: random.Random, spec: SimSpec) -> tuple:
    fam = rng.randint(PARTITION, PACKET)
    mask = rng.randrange(1, 1 << spec.nodes)
    t0 = rng.randrange(spec.txns - 1)
    t1 = t0 + 1 + rng.randrange(max(1, spec.txns // 2))
    p0 = rng.randrange(-2 * spec.mops, 2 * spec.mops + 1)
    p1 = rng.randrange(0, MAX_SPAN + 1)
    return (fam, mask, t0, t1, p0, p1)


def random_schedule(seed: int, spec: SimSpec = DEFAULT_SPEC,
                    families=None) -> np.ndarray:
    """Seeded schedule generation — a pure function of ``seed``.

    ``families`` optionally restricts which fault families may appear
    (names from FAMILIES). Fault count is biased low so single-family
    causes stay attributable, but overlap is common enough to exercise
    fault interactions.
    """
    rng = random.Random(derive_seed(seed, 0x5C4ED))
    allowed = [FAMILY_CODE[f] for f in (families or FAMILIES)]
    sched = empty_schedule(spec)
    n = 1 + min(rng.randrange(spec.faults), rng.randrange(spec.faults))
    for i in range(n):
        slot = list(_random_slot(rng, spec))
        slot[0] = rng.choice(allowed)
        sched[i] = slot
    return canonicalize(sched, spec)


MUTATIONS = ("shift", "widen", "overlap", "retarget", "param", "splice",
             "add", "drop")


def mutate(sched: np.ndarray, seed: int, spec: SimSpec = DEFAULT_SPEC,
           donor: np.ndarray | None = None, families=None) -> np.ndarray:
    """Apply 1–3 seeded mutation operators and re-canonicalize.

    Operators: shift/widen a fault window, force two windows to
    overlap, retarget a node mask, perturb family parameters, splice a
    slot from a donor schedule (grudge splicing), add a fresh fault,
    drop one. A pure function of (sched, seed, donor).
    """
    rng = random.Random(derive_seed(seed, 0x3117A7E))
    s = np.array(sched, dtype=np.int32, copy=True)
    allowed = [FAMILY_CODE[f] for f in (families or FAMILIES)]
    for _ in range(rng.randint(1, 3)):
        active = [i for i in range(spec.faults) if s[i, 0] != NONE]
        op = rng.choice(MUTATIONS)
        if op in ("shift", "widen", "overlap", "retarget", "param",
                  "drop") and not active:
            op = "add"
        if op == "shift":
            i = rng.choice(active)
            d = rng.randint(-spec.txns // 4, spec.txns // 4)
            s[i, 2] += d
            s[i, 3] += d
        elif op == "widen":
            i = rng.choice(active)
            s[i, 2] -= rng.randint(0, spec.txns // 4)
            s[i, 3] += rng.randint(0, spec.txns // 4)
        elif op == "overlap":
            i = rng.choice(active)
            j = rng.choice(active)
            mid = (int(s[i, 2]) + int(s[i, 3])) // 2
            span = max(1, int(s[j, 3]) - int(s[j, 2]))
            s[j, 2] = mid - span // 2
            s[j, 3] = s[j, 2] + span
        elif op == "retarget":
            i = rng.choice(active)
            s[i, 1] = rng.randrange(1, 1 << spec.nodes)
        elif op == "param":
            i = rng.choice(active)
            s[i, rng.choice((4, 5))] += rng.randint(-2, 2)
        elif op == "splice" and donor is not None:
            donor_active = [i for i in range(spec.faults)
                            if donor[i, 0] != NONE]
            if donor_active:
                s[rng.randrange(spec.faults)] = donor[rng.choice(donor_active)]
        elif op == "add":
            free = [i for i in range(spec.faults) if s[i, 0] == NONE]
            i = rng.choice(free) if free else rng.randrange(spec.faults)
            slot = list(_random_slot(rng, spec))
            slot[0] = rng.choice(allowed)
            s[i] = slot
        elif op == "drop":
            s[rng.choice(active)] = 0
    return canonicalize(s, spec)


def fingerprint(sched: np.ndarray, wseed: int) -> str:
    """Content id of one cluster configuration (schedule + workload
    seed) — the corpus dedupe key; stable across processes."""
    h = hashlib.sha1()
    h.update(np.asarray(sched, dtype=np.int32).tobytes())
    h.update(int(wseed).to_bytes(8, "little", signed=False))
    return h.hexdigest()[:16]


def schedule_to_lists(sched: np.ndarray) -> list:
    return [[int(v) for v in row] for row in np.asarray(sched)]


def schedule_from_lists(rows, spec: SimSpec = DEFAULT_SPEC) -> np.ndarray:
    return canonicalize(np.array(rows, dtype=np.int32).reshape(-1, 6), spec)


def families_of(sched: np.ndarray) -> list:
    """Sorted fault-family names present in a schedule."""
    present = {int(f) for f in np.asarray(sched)[:, 0] if int(f) != NONE}
    return [CODE_FAMILY[c] for c in sorted(present)]


def overlap_signature(sched: np.ndarray) -> str:
    """Which fault-family pairs have overlapping windows — a coverage
    feature: fault *interactions* are where the interesting traces
    live, so the corpus keeps one representative per interaction set."""
    s = np.asarray(sched)
    pairs = set()
    active = [i for i in range(s.shape[0]) if int(s[i, 0]) != NONE]
    for a in active:
        for b in active:
            if a >= b:
                continue
            if int(s[a, 2]) < int(s[b, 3]) and int(s[b, 2]) < int(s[a, 3]):
                fa, fb = sorted((int(s[a, 0]), int(s[b, 0])))
                pairs.add((fa, fb))
    return ",".join(f"{a}+{b}" for a, b in sorted(pairs)) or "-"


def _node_names(spec: SimSpec, nodes=None) -> list:
    return list(nodes) if nodes else [f"n{i + 1}" for i in range(spec.nodes)]


def to_nemesis_doc(sched: np.ndarray, spec: SimSpec = DEFAULT_SPEC,
                   nodes=None, interval: float = 5.0, seed: int = 0) -> dict:
    """Render an array schedule as a nemesis/combined.py schedule doc.

    The doc is the same shape ``combined.materialize_schedule``
    produces, so ``combined.schedule_from_json`` (and therefore
    ``jepsen-tpu test --nemesis-schedule``) replays a fuzz-discovered
    schedule through the real nemesis + generator path. One txn-slot
    maps to ``interval`` seconds; each event carries ``dt``, the delay
    before it fires, so relative fault timing survives the transport.
    """
    names = _node_names(spec, nodes)
    rng = random.Random(derive_seed(seed, 0xD0C))
    s = canonicalize(sched, spec)
    timeline = []  # (time_slots, order, event-dict)
    for i in range(spec.faults):
        fam, mask, t0, t1, p0, p1 = (int(v) for v in s[i])
        if fam == NONE:
            continue
        members = [names[n] for n in range(spec.nodes) if mask >> n & 1]
        others = [nm for nm in names if nm not in members]
        if fam == PARTITION:
            grudge = {nm: sorted(others) for nm in members}
            grudge.update({nm: sorted(members) for nm in others})
            timeline.append((t0, i, {"f": "start-partition", "value": grudge}))
            timeline.append((t1, i, {"f": "stop-partition", "value": None}))
        elif fam == CLOCK:
            secs = p0 * interval / spec.mops
            offsets = {nm: round(secs, 6) for nm in members}
            timeline.append((t0, i, {"f": "scramble-clock", "value": offsets}))
            timeline.append((t1, i, {"f": "reset-clock", "value": None}))
        elif fam == KILL:
            timeline.append((t0, i, {"f": "kill", "value": sorted(members)}))
            timeline.append((t1, i, {"f": "restart",
                                     "value": sorted(members)}))
        elif fam == PAUSE:
            timeline.append((t0, i, {"f": "pause", "value": sorted(members)}))
            timeline.append((t1, i, {"f": "resume",
                                     "value": sorted(members)}))
        elif fam == CORRUPT:
            # "path": None is a placeholder — schedule_from_json fills
            # it from opts["corrupt_paths"] at replay time
            specs = [{"node": nm, "path": None, "kind": "bitflip",
                      "offset": p1 * 512 + i,
                      "byte": rng.randrange(256)} for nm in sorted(members)]
            timeline.append((t0, i, {"f": "corrupt-file", "value": specs}))
        elif fam == PACKET:
            # drop rate >= half maps to the lossy behavior, else slow
            behavior = "flaky" if p0 >= 8 else "slow"
            timeline.append((t0, i, {"f": "packet-start",
                                     "value": behavior}))
            timeline.append((t1, i, {"f": "packet-stop", "value": None}))
    timeline.sort(key=lambda e: (e[0], e[1], e[2]["f"]))
    events, prev = [], 0
    for t, _i, evt in timeline:
        events.append({"dt": round((t - prev) * interval, 6), **evt})
        prev = t
    fams = families_of(s)
    final = []
    if "partition" in fams:
        final.append({"dt": 0, "f": "stop-partition", "value": None})
    if "clock" in fams:
        final.append({"dt": 0, "f": "reset-clock", "value": None})
    if "kill" in fams:
        final.append({"dt": 0, "f": "restart", "value": None})
    if "pause" in fams:
        final.append({"dt": 0, "f": "resume", "value": None})
    if "packet" in fams:
        final.append({"dt": 0, "f": "packet-stop", "value": None})
    return {"version": 1,
            "faults": fams,
            "nodes": names,
            "interval": interval,
            "seed": seed,
            "events": events,
            "final": final}


def dump_schedule_file(path, sched: np.ndarray,
                       spec: SimSpec = DEFAULT_SPEC, **kw):
    doc = to_nemesis_doc(sched, spec, **kw)
    with open(path, "w") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")
    return doc
