"""Trace scoring: vectorized sim output -> verdict + coverage.

``decode`` turns one cluster's output arrays back into a standard
invoke/ok list-append history — the same shape live runs and fixtures
use — so the REAL inference path (checker/cycle/deps.extract) and the
real anomaly masks (checker/cycle/anomalies) judge every fuzzed trace;
the fuzzer cannot drift from the checker it is exercising.

``score_batch`` is the batched form of anomalies.classify: it gathers
every trace's component x relation-mask closure jobs into ONE
supervised launch on the closure ladder (largest matrices first, the
same dealing discipline classify uses), then reassembles per-trace
verdicts plus the coverage features the fuzz loop buckets on:

* anomaly class set (G0 / G1c / G-single / G2),
* cycle-participating SCC count and max size (log2 buckets),
* weak component count (log2 bucket),
* edge-relation mix (ww:wr:rw quartile signature),
* fault families + overlap signature of the schedule that produced
  the trace.

A trace's coverage key is the join of those features; the corpus
keeps the first schedule to hit each key.
"""

from __future__ import annotations

import numpy as np

from .. import history as hist_mod
from ..checker.cycle import anomalies as an_mod
from ..checker.cycle import deps as deps_mod
from .schedule import DEFAULT_SPEC, SimSpec, families_of, overlap_signature
from .sim import KIND_APPEND, KIND_READ

#: distinct relation masks classification needs closures of, in the
#: order anomalies._MASKS implies (G0; G1c + G-single; G2).
_MASK_KEYS = (("ww",), ("ww", "wr"), ("ww", "wr", "rw"))


def decode(res: dict, spec: SimSpec = DEFAULT_SPEC) -> list:
    """One cluster's arrays -> an indexed invoke/ok history.

    Failed txns (killed coordinators) are dropped whole — Elle-style
    inference only consumes ok txns. Append values are the globally
    unique vids; a read's value is the prefix of the final per-key
    append order of length ``rlen`` (the sim guarantees prefix
    consistency, so inference cannot raise IllegalInference).
    """
    St, L = spec.slots, spec.mops
    kind = np.asarray(res["kind"])
    key = np.asarray(res["key"])
    pos = np.asarray(res["pos"])
    rlen = np.asarray(res["rlen"])
    coord = np.asarray(res["coord"])
    failed = np.asarray(res["failed"])
    # final per-key append order, from the ranked positions
    orders: dict = {}
    for s in range(St):
        if failed[s]:
            continue
        for j in range(L):
            if kind[s, j] == KIND_APPEND:
                orders.setdefault(int(key[s, j]), []).append(
                    (int(pos[s, j]), s * L + j + 1))
    orders = {k: [vid for _, vid in sorted(v)] for k, v in orders.items()}
    out = []
    for s in range(St):
        if failed[s]:
            continue
        txn = []
        for j in range(L):
            kd = int(kind[s, j])
            k = int(key[s, j])
            if kd == KIND_APPEND:
                txn.append(["append", k, s * L + j + 1])
            elif kd == KIND_READ:
                txn.append(["r", k, list(orders.get(k, [])[:int(rlen[s, j])])])
        if not txn:
            continue
        p = int(coord[s])
        out.append(hist_mod.invoke_op(p, "txn", txn))
        out.append(hist_mod.ok_op(p, "txn", txn))
    return hist_mod.index(out)


def _features(g, closure_full: np.ndarray, comps) -> dict:
    mutual = closure_full & closure_full.T
    on_cycle = np.flatnonzero(np.diag(closure_full))
    sccs = set()
    max_scc = 0
    for i in on_cycle:
        members = frozenset(np.flatnonzero(mutual[i] | (np.arange(
            len(g)) == i)).tolist())
        sccs.add(members)
        max_scc = max(max_scc, len(members))
    return {
        "node-count": len(g),
        "component-count": len(comps),
        "scc-count": len(sccs),
        "max-scc": max_scc,
        "edges": {r: int(g.adj[r].sum()) for r in ("ww", "wr", "rw")},
    }


def _bucket(n: int) -> int:
    return int(n).bit_length()


def _mix_sig(edges: dict) -> str:
    total = sum(edges.values())
    if not total:
        return "0:0:0"
    return ":".join(str(min(3, 4 * edges[r] // total))
                    for r in ("ww", "wr", "rw"))


def coverage_key(score: dict, sched=None) -> str:
    """The corpus bucket a scored trace lands in. Coarse by design:
    log2 buckets and quartile mixes keep the corpus small while still
    separating structurally different traces."""
    types = "+".join(score["anomaly-types"]) or "none"
    parts = [
        f"t={types}",
        f"c={_bucket(score['component-count'])}",
        f"s={_bucket(score['max-scc'])}",
        f"m={_mix_sig(score['edges'])}",
    ]
    if sched is not None:
        parts.append(f"f={'+'.join(families_of(sched)) or 'none'}")
        parts.append(f"o={overlap_signature(sched)}")
    return "|".join(parts)


def score_batch(results: list, spec: SimSpec = DEFAULT_SPEC,
                scheds=None, engine: str | None = None,
                budget: float | None = None) -> list:
    """Score a batch of sim results; one dict per trace:

    {"anomaly-types", "cycle-count", "node-count", "component-count",
     "scc-count", "max-scc", "edges", "coverage", "valid"}.

    All traces' closure jobs go to the closure supervisor as ONE batch
    (engine=None) or a pinned rung ("host"/"tpu"/"mesh" — parity
    tooling). A trace whose inference fails (cannot happen for sim
    traces, but the scorer is also used on foreign fixtures) scores as
    coverage bucket "unknown" rather than poisoning the batch.

    ``budget`` (absolute time.monotonic deadline) bounds the closure
    launch: traces whose closures didn't fit score "unknown" with
    error "deadline" while completed traces score normally — the
    deadline degrades coverage, never correctness.
    """
    graphs: list = [None] * len(results)
    errors: list = [None] * len(results)
    for i, res in enumerate(results):
        try:
            graphs[i] = deps_mod.extract(decode(res, spec))
        except deps_mod.IllegalInference as e:
            errors[i] = str(e)
    jobs: list = []   # (trace index, rels)
    mats: list = []
    per: list = [None] * len(results)
    for gi, g in enumerate(graphs):
        if g is None:
            continue
        masks = {rels: g.union(rels) for rels in _MASK_KEYS}
        comps = an_mod.components(masks[_MASK_KEYS[-1]])
        per[gi] = (masks, comps)
        for rels in _MASK_KEYS:
            for c in comps:
                jobs.append((gi, rels))
                mats.append(masks[rels][np.ix_(c, c)])
    order = sorted(range(len(mats)), key=lambda i: -mats[i].shape[0])
    closed: list = [None] * len(mats)
    subs = an_mod._closures([mats[i] for i in order], engine=engine,
                            budget=budget)
    for i, sub in zip(order, subs):
        closed[i] = sub
    # reassemble per-trace block-diagonal closures; a trace with ANY
    # deadline-expired (None) block degrades to unknown — an
    # incomplete closure can only miss anomalies, never find false
    # ones, so partial blocks must not score
    closures: list = [None] * len(results)
    ji = 0
    for gi, g in enumerate(graphs):
        if g is None:
            continue
        masks, comps = per[gi]
        n = len(g)
        cl = {rels: np.zeros((n, n), dtype=bool) for rels in _MASK_KEYS}
        for rels in _MASK_KEYS:
            for c in comps:
                if closed[ji] is None:
                    cl = None
                elif cl is not None:
                    cl[rels][np.ix_(c, c)] = closed[ji]
                ji += 1
        closures[gi] = cl
        if cl is None:
            errors[gi] = "deadline"
            graphs[gi] = None
    out = []
    for gi, g in enumerate(graphs):
        if g is None:
            score = {"anomaly-types": ["unknown"], "cycle-count": 0,
                     "node-count": 0, "component-count": 0,
                     "scc-count": 0, "max-scc": 0,
                     "edges": {"ww": 0, "wr": 0, "rw": 0},
                     "error": errors[gi], "valid": "unknown",
                     "coverage": "unknown"}
            out.append(score)
            continue
        masks, comps = per[gi]
        cl = closures[gi]
        types = []
        cycles = 0
        claimed = np.zeros((len(g), len(g)), dtype=bool)
        for a in an_mod.ANOMALIES:
            rels, hit_rel = an_mod._MASKS[a]
            hits = g.adj[hit_rel] & cl[tuple(rels)].T
            if a == "G-single":
                claimed |= hits
            elif a == "G2":
                hits = hits & ~claimed
            k = int(hits.sum())
            if k:
                cycles += k
                types.append(a)
        score = {"anomaly-types": types, "cycle-count": cycles,
                 "valid": not types,
                 **_features(g, cl[_MASK_KEYS[-1]], comps)}
        sched = scheds[gi] if scheds is not None else None
        score["coverage"] = coverage_key(score, sched)
        out.append(score)
    return out


def check_trace(res: dict, spec: SimSpec = DEFAULT_SPEC,
                engine: str | None = None) -> dict:
    """Full standard-checker verdict for ONE trace (with witnesses) —
    decode + deps.extract + anomalies.classify, exactly the cycle
    checker's path; used by replay parity and the tutorial."""
    try:
        g = deps_mod.extract(decode(res, spec))
    except deps_mod.IllegalInference as e:
        return {"valid": "unknown", "error": str(e), "anomaly-types": []}
    r = an_mod.classify(g, engine=engine)
    r["valid"] = not r["anomaly-types"]
    return r
