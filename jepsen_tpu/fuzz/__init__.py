"""Million-cluster fault-schedule fuzzing (ROADMAP item 4).

PR 1 made every fault schedule a pure function of ``--seed``; the
``dbs/*_sim`` suites and workloads/list_append's serial-store
simulator made whole runs deterministic and cluster-free. This package
vectorizes that premise: the list-append store simulator and the six
nemesis-family schedules (nemesis/combined.py) are ported to
fixed-shape integer array form so ONE device launch executes thousands
of seeded simulated clusters end-to-end, each under its own fault
schedule — the repo stops checking given histories and starts
*generating* scenario diversity at TPU rate.

The pipeline, one module per stage:

schedule.py   the array encoding of a fault schedule — F fault slots of
              (family, node-mask, window, params) int32 — plus seeded
              generation (pure function of seed), the mutation operators
              the fuzz loop applies (shift/widen/overlap windows, splice
              slots, retarget masks), and the bridge that renders an
              array schedule as a nemesis/combined.py schedule document
              so any fuzz-discovered schedule replays through the REAL
              (non-vectorized) nemesis path via ``--nemesis-schedule``.

sim.py        the vectorized cluster: a batch-first, integer-only
              simulation of N replicated list-append nodes under the
              schedule's faults (partition visibility walls, clock
              skew/strobe reordering commit order, kill windows failing
              txns and redelivering replication, pause splitting a
              txn's micro-ops across time, corruption rolling a
              replica's tail back, packet loss delaying delivery).
              One implementation runs twice: jitted jax as the device
              engine, numpy as the host floor — behind a third
              supervisor singleton (SIM_LADDER: sim_tpu -> sim_host),
              so a mid-fuzz device failure degrades a round to host
              and never poisons the corpus. Every read observes a
              prefix of the final per-key append order by
              construction, so decoded traces are always inferable
              (no IllegalInference), and every anomaly found is real.

score.py      trace -> verdict + coverage: decode each cluster's output
              arrays into a standard invoke/ok history, infer the
              dependency graph (checker/cycle/deps), and classify Adya
              anomalies with ALL clusters' component x mask closures
              batched into ONE supervised launch on the closure ladder.
              Coverage features: anomaly class set, component/SCC
              buckets, edge-relation mix, fault-overlap signature.

loop.py       the coverage-guided mutation loop and the corpus: seed
              schedules + retained mutants keyed by coverage bucket,
              crash-consistent checkpoints (write-temp -> fsync ->
              rename, the PR 5 discipline; a SIGKILL'd round replays
              idempotently from the round counter), and automatic
              commit of every discovered anomaly trace to the
              replay-parity corpus (tools/replay_parity.py's ``fuzz``
              block re-checks them on every engine).

CLI: ``jepsen-tpu fuzz`` (cli.fuzz_cmd). Bench: bench.py's ``fuzz``
lane (simulated clusters/s, time-to-first-anomaly). Docs:
ARCHITECTURE.md "Vectorized cluster fuzzing" and
docs/tutorial/12-fuzzing.md.
"""

from __future__ import annotations

from .schedule import FAMILIES, SimSpec, random_schedule
from .sim import simulate_batch
from .score import decode, score_batch

__all__ = [
    "FAMILIES",
    "SimSpec",
    "decode",
    "random_schedule",
    "score_batch",
    "simulate_batch",
]
