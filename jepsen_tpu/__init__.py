"""jepsen_tpu — a TPU-native distributed-systems-testing framework.

A ground-up redesign of the capabilities of Jepsen (reference:
/root/reference, jepsen/src/jepsen/core.clj) for the JAX/XLA/TPU era:

- the *control plane* (cluster provisioning, fault injection, concurrent
  workload execution) is host-side Python with pluggable remote backends;
- the *data plane* is a flat structure-of-arrays int64 tensor encoding of
  operation histories, shared between the engine, the store, and the
  checkers;
- the *analysis plane* runs on TPU: consistency checkers are jitted /
  vmapped kernels, and the Wing-Gong-Lowe linearizability search (the
  knossos equivalent) is a bitmask-DFS kernel with its memo cache in HBM,
  sharded over independent keys via a jax.sharding.Mesh.

Top-level namespaces mirror the reference's layer map (SURVEY.md SS1):

    history     op records + invoke/complete pairing   (knossos.history)
    models      consistency models as step functions   (knossos.model)
    checker     Checker protocol + built-in checkers   (jepsen.checker)
    ops         TPU kernels (WGL search, scans)        (knossos.wgl/linear)
    generator   op-scheduling DSL                      (jepsen.generator)
    independent key-space sharding                     (jepsen.independent)
    client      Client protocol                        (jepsen.client)
    core        test orchestration / run()             (jepsen.core)
    control     remote execution                       (jepsen.control)
    nemesis     fault injection                        (jepsen.nemesis)
    net         network partitions / degradation       (jepsen.net)
    db, osenv   node lifecycle                         (jepsen.db, jepsen.os)
    store       persistence & reporting                (jepsen.store)
    cli         command-line runners                   (jepsen.cli)
"""

__version__ = "0.1.0"
