"""Remote install/daemon helpers (reference: jepsen.control.util,
control/util.clj:1-264).

All functions take an explicit (remote, node) pair — the framework passes
state explicitly instead of the reference's dynamic vars — and otherwise
keep the reference's semantics: /tmp/jepsen scratch space, cached wgets
keyed by base64 URL, archive installs that flatten a single top-level
directory, start-stop-daemon-style daemon management."""

from __future__ import annotations

import base64
import logging
import os.path
import random

from . import Remote, RemoteError

log = logging.getLogger("jepsen_tpu.control.util")

#: scratch space on nodes (control/util.clj:10)
TMP_DIR_BASE = "/tmp/jepsen"

#: wget cache (control/util.clj:75-77)
WGET_CACHE_DIR = f"{TMP_DIR_BASE}/wget-cache"

#: standard wget retry options (control/util.clj:53-60)
STD_WGET_OPTS = [
    "--tries", "20",
    "--waitretry", "60",
    "--retry-connrefused",
    "--dns-timeout", "60",
    "--connect-timeout", "60",
    "--read-timeout", "60",
]


def exists(remote: Remote, node, path) -> bool:
    """Is a path present (control/util.clj:18-23)?"""
    return remote.exec(node, ["stat", str(path)], check=False).exit == 0


def ls(remote: Remote, node, directory=".") -> list[str]:
    """Directory entries, without . and .. (control/util.clj:25-31)."""
    out = remote.exec(node, ["ls", "-A", str(directory)]).out
    return [line for line in out.splitlines() if line.strip()]


def ls_full(remote: Remote, node, directory) -> list[str]:
    """ls with the directory prepended (control/util.clj:33-41)."""
    d = str(directory)
    if not d.endswith("/"):
        d += "/"
    return [d + e for e in ls(remote, node, d)]


def tmp_dir(remote: Remote, node) -> str:
    """A fresh temporary directory under /tmp/jepsen
    (control/util.clj:43-51)."""
    while True:
        d = f"{TMP_DIR_BASE}/{random.randrange(2**31)}"
        if not exists(remote, node, d):
            remote.exec(node, ["mkdir", "-p", d])
            return d


def wget(remote: Remote, node, url: str, force: bool = False) -> str:
    """Download url into the cwd, skipping if present; returns the
    filename (control/util.clj:62-73)."""
    filename = os.path.basename(url)
    if force:
        remote.exec(node, ["rm", "-f", filename])
    if not exists(remote, node, filename):
        remote.exec(node, ["wget", *STD_WGET_OPTS, url])
    return filename


def cached_wget(remote: Remote, node, url: str, force: bool = False) -> str:
    """Download url into the wget cache, named by its base64-encoded URL
    (so versions living in the path, not the filename, still get distinct
    cache entries); returns the full path (control/util.clj:79-104)."""
    encoded = base64.b64encode(url.encode()).decode()
    dest = f"{WGET_CACHE_DIR}/{encoded}"
    if force:
        log.info("Clearing cached copy of %s", url)
        remote.exec(node, ["rm", "-rf", dest])
    if not exists(remote, node, dest):
        log.info("Downloading %s", url)
        remote.exec(node, ["mkdir", "-p", WGET_CACHE_DIR])
        remote.exec(
            node, ["wget", *STD_WGET_OPTS, "-O", dest, url], cd=WGET_CACHE_DIR
        )
    return dest


def install_archive(
    remote: Remote,
    node,
    url: str,
    dest: str,
    force: bool = False,
    sudo=None,
    _retried: bool = False,
) -> str:
    """Fetch a zip/tarball (cached) and extract it to dest, replacing
    dest's contents; a sole top-level directory is flattened into dest.
    Corrupt cached downloads are re-fetched once
    (control/util.clj:106-173)."""
    local_file = url[len("file://"):] if url.startswith("file://") else None
    archive = local_file or cached_wget(remote, node, url, force=force)
    tmpdir = tmp_dir(remote, node)
    remote.exec(node, ["rm", "-rf", dest], sudo=sudo)
    remote.exec(node, ["mkdir", "-p", os.path.dirname(dest) or "/"], sudo=sudo)
    try:
        if url.endswith(".zip"):
            remote.exec(node, ["unzip", archive], cd=tmpdir)
        else:
            remote.exec(
                node,
                ["tar", "--no-same-owner", "--no-same-permissions",
                 "--extract", "--file", archive],
                cd=tmpdir,
            )
        if sudo:
            remote.exec(node, ["chown", "-R", "root:root", "."],
                        cd=tmpdir, sudo=sudo)
        roots = ls(remote, node, tmpdir)
        if not roots:
            raise RemoteError("Archive contained no files")
        if len(roots) == 1:
            remote.exec(node, ["mv", f"{tmpdir}/{roots[0]}", dest], sudo=sudo)
        else:
            remote.exec(node, ["mv", tmpdir, dest], sudo=sudo)
        return dest
    except RemoteError as e:
        if "Unexpected EOF" in str(e):
            if local_file:
                raise RemoteError(
                    f"Local archive {local_file} on node {node} is corrupt: "
                    "unexpected EOF."
                ) from e
            if not _retried:
                log.info("Retrying corrupt archive download")
                remote.exec(node, ["rm", "-rf", archive])
                return install_archive(
                    remote, node, url, dest, force=force, sudo=sudo,
                    _retried=True,
                )
        raise
    finally:
        remote.exec(node, ["rm", "-rf", tmpdir], check=False)


def ensure_user(remote: Remote, node, username: str) -> str:
    """Make sure a user exists (control/util.clj:182-189)."""
    r = remote.exec(
        node,
        ["adduser", "--disabled-password", "--gecos", "", username],
        sudo=True,
        check=False,
    )
    if r.exit != 0 and "already exists" not in (r.err + r.out):
        r.throw()
    return username


def grepkill(remote: Remote, node, pattern: str, signal: int = 9) -> None:
    """Kill processes whose ps line matches pattern
    (control/util.clj:191-206)."""
    remote.exec(
        node,
        f"ps aux | grep {pattern} | grep -v grep | awk '{{print $2}}' "
        f"| xargs -r kill -{signal}",
        check=False,
    )


def start_daemon(
    remote: Remote,
    node,
    bin: str,
    *args,
    logfile: str,
    pidfile: str,
    chdir: str = "/",
    background: bool = True,
    make_pidfile: bool = True,
    match_executable: bool = True,
    match_process_name: bool = False,
    process_name: str | None = None,
    env: dict | None = None,
) -> None:
    """Start a daemon via start-stop-daemon, appending stdout/stderr to
    logfile (control/util.clj:208-236)."""
    log.info("starting %s", os.path.basename(bin))
    remote.exec(
        node,
        f"echo \"`date +'%Y-%m-%d %H:%M:%S'` Jepsen starting {bin} "
        f"{' '.join(str(a) for a in args)}\" >> {logfile}",
    )
    argv = ["start-stop-daemon", "--start"]
    if background:
        argv += ["--background", "--no-close"]
    if make_pidfile:
        argv += ["--make-pidfile"]
    if match_executable:
        argv += ["--exec", bin]
    if match_process_name:
        argv += ["--name", process_name or os.path.basename(bin)]
    argv += ["--pidfile", pidfile, "--chdir", chdir, "--oknodo",
             "--startas", bin, "--"]
    argv += [str(a) for a in args]
    cmd = " ".join(argv) + f" >> {logfile} 2>&1"
    if env:
        exports = " ".join(f"{k}={v}" for k, v in env.items())
        cmd = f"env {exports} {cmd}"
    remote.exec(node, cmd)


def stop_daemon(remote: Remote, node, pidfile: str, cmd: str | None = None
                ) -> None:
    """Kill a daemon by pidfile — or by command name, if given — and
    remove the pidfile (control/util.clj:238-251)."""
    if cmd is not None:
        log.info("Stopping %s", cmd)
        remote.exec(node, ["killall", "-9", "-w", cmd], check=False)
        remote.exec(node, ["rm", "-rf", pidfile], check=False)
        return
    if exists(remote, node, pidfile):
        log.info("Stopping %s", pidfile)
        pid = remote.exec(node, ["cat", pidfile]).out.strip()
        if pid:
            remote.exec(node, ["kill", "-9", pid], check=False)
        remote.exec(node, ["rm", "-rf", pidfile], check=False)


def daemon_running(remote: Remote, node, pidfile: str) -> bool | None:
    """True if pidfile names a live process, None if no pidfile, False if
    the process is gone (control/util.clj:253-264)."""
    r = remote.exec(node, ["cat", pidfile], check=False)
    if r.exit != 0 or not r.out.strip():
        return None
    return remote.exec(
        node, ["ps", "-o", "pid=", "-p", r.out.strip()], check=False
    ).exit == 0
