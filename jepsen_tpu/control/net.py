"""Node network helpers (reference: jepsen.control.net, control/net.clj)."""

from __future__ import annotations

import threading

_ip_cache: dict = {}
_lock = threading.Lock()


def ip(test, node) -> str:
    """Resolve a node's IP from the control plane's perspective, memoized
    (control/net.clj:21-34)."""
    key = (id(test.get("remote")), node)
    with _lock:
        if key in _ip_cache:
            return _ip_cache[key]
    from . import DummyRemote, LocalRemote

    remote = test["remote"]
    if isinstance(remote, (DummyRemote, LocalRemote)):
        addr = "127.0.0.1"
    else:
        r = remote.exec(
            node,
            ["getent", "ahostsv4", str(node)],
            check=False,
        )
        addr = r.out.split()[0] if r.ok and r.out else str(node)
    with _lock:
        _ip_cache[key] = addr
    return addr


def reachable(test, from_node, to_node) -> bool:
    """Can from_node ping to_node? (control/net.clj:7-11)"""
    r = test["remote"].exec(
        from_node, ["ping", "-w", "1", "-c", "1", str(to_node)], check=False
    )
    return r.ok
