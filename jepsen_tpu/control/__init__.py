"""Remote execution: the control plane (reference: jepsen.control,
control.clj).

The reference drives nodes over SSH (JSch) with shell escaping, sudo
wrapping, retries, and scp. Here the transport is a pluggable `Remote`:

  SshRemote    shells out to the system ssh/scp binaries (OpenSSH),
               persistent via ControlMaster when available
  LocalRemote  runs commands in per-node sandbox directories on this
               machine via subprocess — hermetic multi-"node" testing
               without any cluster (the analog of docker/lxc setups,
               docker/README.md:1-22)
  DummyRemote  records commands and returns empty output
               (control.clj *dummy*, control.clj:16,288-300)

All higher layers (os/db/net/nemesis) talk to test["remote"], never to a
transport directly.
"""

from __future__ import annotations

import logging
import os
import shlex
import subprocess
import threading
import time
from dataclasses import dataclass, field
from typing import Mapping, Sequence

from ..util import with_retry

log = logging.getLogger("jepsen_tpu.control")


@dataclass
class Result:
    out: str
    err: str
    exit: int
    cmd: str = ""

    @property
    def ok(self) -> bool:
        return self.exit == 0

    def throw(self) -> "Result":
        if not self.ok:
            raise RemoteError(
                f"command failed ({self.exit}): {self.cmd}\n{self.err or self.out}"
            )
        return self


class RemoteError(Exception):
    pass


def escape(arg) -> str:
    """Shell-escape one argument (control.clj:54-97). Sequences are
    joined with spaces after escaping each element."""
    if isinstance(arg, (list, tuple)):
        return " ".join(escape(a) for a in arg)
    return shlex.quote(str(arg))


def wrap_sudo(cmd: str, user: str = "root") -> str:
    """Wrap a shell command in sudo (control.clj:99-107)."""
    return f"sudo -S -u {user} bash -c {shlex.quote(cmd)}"


def wrap_cd(cmd: str, directory: str | None) -> str:
    """Prefix with a cd (control.clj:109-114)."""
    if not directory:
        return cmd
    return f"cd {shlex.quote(str(directory))} && {cmd}"


def build_cmd(cmd, sudo=None, cd=None) -> str:
    s = cmd if isinstance(cmd, str) else " ".join(escape(c) for c in cmd)
    s = wrap_cd(s, cd)
    if sudo:
        s = wrap_sudo(s, "root" if sudo is True else sudo)
    return s


class Remote:
    """Transport interface. exec() raises RemoteError on nonzero exit
    unless check=False."""

    def connect(self, node) -> None:
        pass

    def disconnect(self, node) -> None:
        pass

    def exec(
        self,
        node,
        cmd,
        sudo=None,
        cd=None,
        stdin: str | None = None,
        timeout: float | None = None,
        check: bool = True,
        retries: int = 0,
    ) -> Result:
        raise NotImplementedError

    def upload(self, node, local_path, remote_path) -> None:
        raise NotImplementedError

    def download(self, node, remote_path, local_path) -> None:
        raise NotImplementedError


class DummyRemote(Remote):
    """Records every command; returns empty success results
    (control.clj *dummy* mode)."""

    def __init__(self):
        self.commands: list = []
        self.uploads: list = []
        self.downloads: list = []
        self._lock = threading.Lock()

    def exec(self, node, cmd, sudo=None, cd=None, stdin=None, timeout=None,
             check=True, retries=0) -> Result:
        full = build_cmd(cmd, sudo, cd)
        with self._lock:
            self.commands.append((node, full))
        return Result("", "", 0, full)

    def upload(self, node, local_path, remote_path):
        with self._lock:
            self.uploads.append((node, str(local_path), str(remote_path)))

    def download(self, node, remote_path, local_path):
        with self._lock:
            self.downloads.append((node, str(remote_path), str(local_path)))


class LocalRemote(Remote):
    """Each "node" is a sandbox directory on this machine; commands run
    there via bash. sudo is a no-op wrapper (we're already the only
    user). Hermetic substitute for a container cluster."""

    def __init__(self, root: str | None = None):
        self.root = root or os.path.join(
            os.environ.get("TMPDIR", "/tmp"), "jepsen-tpu-nodes"
        )

    def node_dir(self, node) -> str:
        d = os.path.join(self.root, str(node))
        os.makedirs(d, exist_ok=True)
        return d

    def exec(self, node, cmd, sudo=None, cd=None, stdin=None, timeout=None,
             check=True, retries=0) -> Result:
        full = build_cmd(cmd, sudo=None, cd=cd)  # sudo elided locally

        def attempt():
            p = subprocess.run(
                ["bash", "-c", full],
                cwd=self.node_dir(node),
                input=stdin,
                capture_output=True,
                text=True,
                timeout=timeout,
                env={**os.environ, "JEPSEN_NODE": str(node)},
            )
            r = Result(p.stdout.strip(), p.stderr.strip(), p.returncode, full)
            return r.throw() if check else r

        return with_retry(attempt, retries=retries, exceptions=(RemoteError,))

    def upload(self, node, local_path, remote_path):
        import shutil

        dest = self._abs(node, remote_path)
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        shutil.copy(local_path, dest)

    def download(self, node, remote_path, local_path):
        import shutil

        os.makedirs(os.path.dirname(str(local_path)) or ".", exist_ok=True)
        shutil.copy(self._abs(node, remote_path), local_path)

    def _abs(self, node, path) -> str:
        path = str(path)
        nd = os.path.abspath(self.node_dir(node))
        if os.path.isabs(path):
            # Paths already inside the sandbox pass through (tests hand
            # DBs absolute sandbox dirs); anything else is confined.
            ap = os.path.abspath(path)
            if ap == nd or ap.startswith(nd + os.sep):
                return ap
            return os.path.join(nd, path.lstrip("/"))
        return os.path.join(nd, path)


class SshRemote(Remote):
    """OpenSSH subprocess transport with retry-on-corruption
    (control.clj:141-161) and scp file transfer (control.clj:199-231).

    Persistent by default via OpenSSH connection multiplexing: every
    exec/scp shares one master connection per node (ControlMaster=auto +
    ControlPath socket + ControlPersist), the analog of the reference's
    one JSch session per node held for the whole test (core.clj:611-620).
    connect() primes the master so nemesis grudges touching many nodes
    pay the handshake once, not per command."""

    def __init__(
        self,
        username: str = "root",
        port: int = 22,
        private_key_path: str | None = None,
        strict_host_key_checking: bool = False,
        connect_timeout: int = 10,
        control_master: bool = True,
        control_persist: int = 60,
    ):
        self.username = username
        self.port = port
        self.private_key_path = private_key_path
        self.strict = strict_host_key_checking
        self.connect_timeout = connect_timeout
        self.control_master = control_master
        self.control_persist = control_persist
        self._control_dir: str | None = None
        self._lock = threading.Lock()

    def _control_path_dir(self) -> str:
        """Socket dir, created lazily (kept short: unix socket paths cap
        out near 104 bytes)."""
        with self._lock:
            if self._control_dir is None:
                import shutil
                import tempfile
                import weakref

                self._control_dir = tempfile.mkdtemp(prefix="jt-cm-")
                weakref.finalize(
                    self, shutil.rmtree, self._control_dir,
                    ignore_errors=True,
                )
            return self._control_dir

    def _opts(self) -> list:
        o = [
            "-o", f"ConnectTimeout={self.connect_timeout}",
            "-o", "BatchMode=yes",
            "-p", str(self.port),
        ]
        if not self.strict:
            o += ["-o", "StrictHostKeyChecking=no",
                  "-o", "UserKnownHostsFile=/dev/null", "-o", "LogLevel=ERROR"]
        if self.private_key_path:
            o += ["-i", self.private_key_path]
        if self.control_master:
            o += [
                "-o", "ControlMaster=auto",
                "-o", f"ControlPath={self._control_path_dir()}/%C",
                "-o", f"ControlPersist={self.control_persist}",
                # a mux'd command has no fresh TCP connect, so
                # ConnectTimeout can't bound it — keepalives detect a
                # dead/black-holed master instead (~15s)
                "-o", "ServerAliveInterval=5",
                "-o", "ServerAliveCountMax=3",
            ]
        return o

    def connect(self, node) -> None:
        """Prime the per-node master connection (core.clj:611-620 opens
        one session per node up front); raises if the node is
        unreachable, like the reference's with-ssh."""
        self.exec(node, ["true"], retries=1)

    def disconnect(self, node) -> None:
        """Ask the master for this node to exit; best-effort."""
        if not self.control_master or self._control_dir is None:
            return
        try:
            subprocess.run(
                ["ssh", *self._opts(), "-O", "exit",
                 f"{self.username}@{node}"],
                capture_output=True, text=True, timeout=10,
            )
        except Exception:  # noqa: BLE001
            log.debug("ssh -O exit failed for %s", node, exc_info=True)

    def exec(self, node, cmd, sudo=None, cd=None, stdin=None, timeout=None,
             check=True, retries=3) -> Result:
        full = build_cmd(cmd, sudo, cd)
        argv = ["ssh", *self._opts(), f"{self.username}@{node}", full]

        def attempt():
            p = subprocess.run(
                argv, input=stdin, capture_output=True, text=True,
                timeout=timeout,
            )
            if p.returncode == 255:  # ssh transport failure: retry
                raise RemoteError(f"ssh transport failure: {p.stderr}")
            r = Result(p.stdout.strip(), p.stderr.strip(), p.returncode, full)
            return r.throw() if check else r

        return with_retry(
            attempt, retries=retries, backoff=0.5, exceptions=(RemoteError,)
        )

    def _scp(self, src, dest):
        opts = self._opts()
        # scp spells the port flag -P, ssh spells it -p
        opts[opts.index("-p")] = "-P"
        p = subprocess.run(
            ["scp", "-q", *opts, src, dest], capture_output=True, text=True
        )
        if p.returncode != 0:
            raise RemoteError(f"scp failed: {p.stderr}")

    def upload(self, node, local_path, remote_path):
        self._scp(str(local_path), f"{self.username}@{node}:{remote_path}")

    def download(self, node, remote_path, local_path):
        self._scp(f"{self.username}@{node}:{remote_path}", str(local_path))


def remote_for_test(test: Mapping) -> Remote:
    """Pick the remote: an explicit test["remote"], else SSH when
    credentials are given, else dummy (control.clj with-ssh + *dummy*)."""
    r = test.get("remote")
    if r is not None:
        return r
    ssh = test.get("ssh") or {}
    if ssh.get("dummy", False) or not ssh:
        return DummyRemote()
    return SshRemote(
        username=ssh.get("username", "root"),
        port=ssh.get("port", 22),
        private_key_path=ssh.get("private_key_path"),
        strict_host_key_checking=ssh.get("strict_host_key_checking", False),
        control_master=ssh.get("control_master", True),
        control_persist=ssh.get("control_persist", 60),
    )


def on_nodes(test, fn, nodes=None) -> dict:
    """Run fn(test, node) on each node in parallel; returns {node: result}
    (control.clj:345-381)."""
    from ..util import real_pmap

    nodes = list(nodes if nodes is not None else test["nodes"])
    results = real_pmap(lambda n: fn(test, n), nodes)
    return dict(zip(nodes, results))
